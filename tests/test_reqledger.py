"""Per-request attribution ledger (ISSUE 17 tentpole): every request's
end-to-end latency decomposes into queue / prefill / decode / guardrail
time that sums to e2e BY CONSTRUCTION — through aborts, retries and
hedging — with one flow id joining its fleet-side instants across
replicas, bounded per-request memory under the event cap, a working
``TDX_REQUEST_LEDGER=0`` kill switch, live ``/requests`` + ``/tail``
endpoints, ledger state folded into flight dumps, and
``tdx_trace.py autopsy`` reconstructing a hedged + chaos-killed +
requeued request as one coherent timeline from the flushed trace."""

from __future__ import annotations

import glob
import json
import os
import subprocess
import sys
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax.numpy as jnp

import torchdistx_tpu.config as tdx_config
from torchdistx_tpu import chaos, observe
from torchdistx_tpu.models import TransformerConfig
from torchdistx_tpu.observe import httpd, reqledger
from torchdistx_tpu.serve import (
    FleetConfig,
    GuardrailConfig,
    Request,
    ServeConfig,
    ServeFleet,
    oracle_generate,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CLI = os.path.join(REPO, "tools", "tdx_trace.py")

LLAMA = TransformerConfig(
    vocab_size=128, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
    d_ff=64, max_seq_len=64, dtype=jnp.float32,
)
SCFG = ServeConfig(max_batch=2, page_size=8, n_pages=16,
                   max_pages_per_seq=3, prefill_buckets=(8, 16))


@pytest.fixture(scope="module")
def shared_cache(tmp_path_factory):
    """One persistent compile cache for the fleet test in this module
    (same contract as tests/test_fleet.py's fixture)."""
    d = str(tmp_path_factory.mktemp("ledger_cache"))
    old = os.environ.get("TDX_CACHE_MIN_COMPILE_S")
    os.environ["TDX_CACHE_MIN_COMPILE_S"] = "0"
    yield d
    if old is None:
        os.environ.pop("TDX_CACHE_MIN_COMPILE_S", None)
    else:
        os.environ["TDX_CACHE_MIN_COMPILE_S"] = old


@pytest.fixture()
def ledger():
    """Telemetry on, ledger empty; everything torn down afterwards."""
    observe.enable(True)
    observe.reset()
    yield
    observe.enable(None)
    observe.reset()


def _stage_sum(summ: dict) -> float:
    return sum(summ[f"{st}_s"] for st in reqledger.STAGES)


def _kinds(detail: dict):
    return [e["k"] for e in detail["events"]]


# ---------------------------------------------------------------------------
# the stage machine: attribution sums to e2e by construction
# ---------------------------------------------------------------------------


def test_attribution_sums_through_abort_and_retry(ledger):
    """An aborted attempt's prefill+decode folds into guardrail time and
    the stage machine returns to queue — the four stages still sum to
    the end-to-end latency, and the retry counts as a second attempt."""
    rid = "att-1"
    reqledger.on_enqueue(rid, priority=0, n_prompt=4)
    time.sleep(0.004)                      # queue
    reqledger.on_admit(rid, replica="serve-r1", prefix_tokens=2)
    time.sleep(0.004)                      # attempt 1 prefill
    reqledger.on_decode(rid, n_lanes=2, replica="serve-r1")
    time.sleep(0.004)                      # attempt 1 decode
    reqledger.on_abort(rid, replica="serve-r1", reason="replica_dead")
    time.sleep(0.004)                      # re-queued
    reqledger.on_admit(rid, replica="serve-r2")
    time.sleep(0.002)
    reqledger.on_decode(rid, n_lanes=1, replica="serve-r2")
    reqledger.on_finish(rid, replica="serve-r2", tokens=5)

    summ = reqledger.summary(rid)
    assert summ is not None and summ["outcome"] == "ok"
    assert summ["attempts"] == 2
    assert summ["prefix_tokens"] == 2
    assert summ["guardrail_s"] > 0.0       # the dead attempt's spent work
    assert summ["queue_s"] > 0.0           # initial wait + requeue gap
    assert abs(_stage_sum(summ) - summ["e2e_s"]) < 1e-4, summ
    ks = _kinds(summ)
    assert ks[0] == "enqueue" and ks[-1] == "finish"
    assert "abort" in ks and ks.count("admit") == 2


def test_hedge_loser_abort_is_an_event_not_a_stage_change(ledger):
    """While the hedge winner is still running, the loser's cancel must
    not reopen the queue stage or fold an attempt — it is timeline
    evidence only.  The winner's time lands in prefill/decode and
    guardrail stays zero."""
    rid = "hedge-1"
    reqledger.on_enqueue(rid)
    reqledger.on_event(rid, "hedge", primary=1, mate=2)
    reqledger.on_admit(rid, replica="serve-r1")
    reqledger.on_admit(rid, replica="serve-r2")   # the hedge mate admits too
    reqledger.on_decode(rid, n_lanes=1, replica="serve-r1")
    reqledger.on_event(rid, "hedge_win", replica=1)
    reqledger.on_abort(rid, replica="serve-r2", reason="hedge_lost")
    time.sleep(0.002)
    reqledger.on_decode(rid, n_lanes=1, replica="serve-r1")
    reqledger.on_finish(rid, replica="serve-r1", tokens=2)

    summ = reqledger.summary(rid)
    assert summ["hedged"] is True
    assert summ["attempts"] == 1          # one externally-visible attempt
    assert summ["guardrail_s"] == 0.0     # loser cancelled while winner ran
    assert summ["decode_s"] > 0.0
    assert abs(_stage_sum(summ) - summ["e2e_s"]) < 1e-4, summ


def test_decode_ticks_coalesce_into_one_event(ledger):
    """A long generation is one timeline slot, not one per token; an
    interleaved event (a COW copy) opens a fresh coalesced stretch."""
    rid = "dc-1"
    reqledger.on_enqueue(rid)
    reqledger.on_admit(rid, replica="serve-r1")
    for _ in range(50):
        reqledger.on_decode(rid, n_lanes=2, replica="serve-r1")
    reqledger.on_cow(rid, replica="serve-r1")
    for _ in range(3):
        reqledger.on_decode(rid, n_lanes=2, replica="serve-r1")
    reqledger.on_finish(rid, tokens=53)

    detail = reqledger.summary(rid)
    assert _kinds(detail) == ["enqueue", "admit", "decode", "cow",
                              "decode", "finish"]
    first, second = [e for e in detail["events"] if e["k"] == "decode"]
    assert first["ticks"] == 50 and first["toks"] == 50
    assert second["ticks"] == 3
    assert detail["tokens"] == 53
    assert detail["cow_copies"] == 1


def test_spec_ticks_coalesce_into_verify_events_in_decode_stage(ledger):
    """Speculative verify ticks are the decode stage's sibling event
    (ISSUE 19): they coalesce like decode ticks, tally drafted /
    accepted / emitted, keep the four-stage sum-to-e2e contract, and
    surface per-request speculation totals in the summary."""
    rid = "sp-1"
    reqledger.on_enqueue(rid)
    reqledger.on_admit(rid, replica="serve-r1")
    time.sleep(0.002)                      # prefill
    for _ in range(4):
        reqledger.on_spec(rid, drafted=3, accepted=2, emitted=3,
                          n_lanes=2, replica="serve-r1")
    reqledger.on_cow(rid, replica="serve-r1")
    reqledger.on_spec(rid, drafted=2, accepted=0, emitted=1, n_lanes=1)
    reqledger.on_decode(rid, n_lanes=1)    # a plain tick interleaves fine
    time.sleep(0.002)                      # decode
    reqledger.on_finish(rid, tokens=14)

    detail = reqledger.summary(rid)
    assert _kinds(detail) == ["enqueue", "admit", "verify", "cow",
                              "verify", "decode", "finish"]
    first, second = [e for e in detail["events"] if e["k"] == "verify"]
    assert first["ticks"] == 4 and first["drafted"] == 12
    assert first["accepted"] == 8 and first["toks"] == 12
    assert second["ticks"] == 1 and second["accepted"] == 0
    assert detail["tokens"] == 14
    assert detail["spec_drafted"] == 14
    assert detail["spec_accepted"] == 8
    assert detail["spec_ticks"] == 5
    # spec time lands in decode: the stage sum stays exact
    assert detail["decode_s"] > 0.0
    assert abs(_stage_sum(detail) - detail["e2e_s"]) < 1e-4, detail


def test_summary_omits_spec_fields_without_speculation(ledger):
    """Plain-decode requests carry no speculation keys — the summary
    vocabulary only grows where spec actually ran (TDX_SPEC_DECODE=0
    keeps old dashboards byte-identical)."""
    rid = "nosp-1"
    reqledger.on_enqueue(rid)
    reqledger.on_admit(rid, replica="serve-r1")
    reqledger.on_decode(rid, n_lanes=1)
    reqledger.on_finish(rid, tokens=1)
    detail = reqledger.summary(rid)
    assert "spec_ticks" not in detail
    assert "spec_drafted" not in detail and "spec_accepted" not in detail


def test_autopsy_reports_spec_summary(ledger, tmp_path):
    """``tdx_trace.py autopsy`` surfaces the request's speculation
    tallies and the coalesced verify event from the flushed terminal
    instant."""
    trace_dir = tmp_path / "traces"
    rid = "sp-auto"
    reqledger.on_enqueue(rid)
    reqledger.on_admit(rid, replica="serve-r1")
    reqledger.on_spec(rid, drafted=4, accepted=3, emitted=4, n_lanes=1)
    reqledger.on_finish(rid, tokens=4)
    observe.flush(trace_dir=str(trace_dir))
    proc = subprocess.run(
        [sys.executable, CLI, "autopsy", rid, str(trace_dir)],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    assert "speculation: drafted=4  accepted=3" in proc.stdout
    assert "verify" in proc.stdout


def test_event_timeline_bounded_with_drop_count(ledger):
    """``TDX_LEDGER_EVENTS`` caps per-request memory: overflow evicts
    the oldest events and counts them, never grows without bound."""
    with tdx_config.override(ledger_events=8):
        rid = "cap-1"
        reqledger.on_enqueue(rid)
        reqledger.on_admit(rid, replica="serve-r1")
        for _ in range(20):
            reqledger.on_cow(rid, replica="serve-r1")
        reqledger.on_finish(rid, tokens=1)
        detail = reqledger.summary(rid)
    assert len(detail["events"]) == 8
    assert detail["events_dropped"] == 15   # 22 appends - 8 kept + terminal
    assert detail["events"][-1]["k"] == "finish"   # terminal never dropped


def test_kill_switch_records_nothing(ledger):
    """``TDX_REQUEST_LEDGER=0``: every hook degrades to one enabled
    check; no records, no flow ids, no finished count."""
    with tdx_config.override(request_ledger=False):
        assert not reqledger.enabled()
        reqledger.on_enqueue("ks-1", priority=0)
        reqledger.on_admit("ks-1", replica="serve-r1")
        reqledger.on_decode("ks-1", n_lanes=1)
        reqledger.on_spec("ks-1", drafted=2, accepted=1, emitted=2,
                          n_lanes=1)
        reqledger.on_finish("ks-1", tokens=1)
        reqledger.occupancy_sample(decode_busy=1, decode_lanes=2)
    assert reqledger.summary("ks-1") is None
    assert reqledger.flow_id("ks-1") is None
    rep = reqledger.requests_report()
    assert rep["finished"] == 0 and not rep["live"] and not rep["recent"]
    assert reqledger.occupancy_report()["count"] == 0
    assert reqledger.enabled()   # back on outside the override


def test_finalize_is_idempotent_and_door_rejects_record(ledger):
    """Racing terminal paths (engine deadline + fleet reject) finalize
    once; a reject with no prior record (brownout at the door) still
    lands a typed zero-duration terminal in the tail window."""
    rid = "fin-1"
    reqledger.on_enqueue(rid)
    reqledger.on_admit(rid, replica="serve-r1")
    reqledger.on_finish(rid, tokens=1)
    reqledger.on_finish(rid, tokens=1)                  # duplicate
    reqledger.on_reject(rid, reason="deadline")         # racing path
    assert reqledger.requests_report()["finished"] == 1

    reqledger.on_reject("door-1", reason="queue_full")
    rep = reqledger.requests_report()
    assert rep["finished"] == 2
    tail = reqledger.tail_report()
    assert tail["outcomes"].get("queue_full") == 1
    assert tail["outcomes"].get("ok") == 1


def test_flow_id_minted_once_and_survives_finish(ledger):
    """The flow id is the request's cross-replica join key: minted at
    enqueue, stable through finish, paired start/finish flow events in
    the tracer, and stamped on the terminal ``serve.request`` instant
    along with the full attribution detail."""
    rid = "flow-1"
    reqledger.on_enqueue(rid)
    flow = reqledger.flow_id(rid)
    assert flow is not None
    reqledger.on_admit(rid, replica="serve-r1")
    reqledger.on_decode(rid, n_lanes=1)
    reqledger.on_finish(rid, tokens=1)
    assert reqledger.flow_id(rid) == flow   # recent ring still answers

    events = observe.tracer().drain()
    starts = [e for e in events if e.get("ph") == "s"
              and e.get("id") == flow]
    finishes = [e for e in events if e.get("ph") == "f"
                and e.get("id") == flow]
    assert len(starts) == 1 and len(finishes) == 1
    assert starts[0]["name"] == "tdx.serve.request"
    term = [e for e in events if e.get("ph") == "i"
            and e.get("name") == "serve.request"
            and (e.get("args") or {}).get("rid") == rid]
    assert len(term) == 1
    args = term[0]["args"]
    assert args["flow"] == flow and args["outcome"] == "ok"
    assert [ev["k"] for ev in args["events"]][-1] == "finish"


def test_stage_histograms_emitted_on_finish(ledger):
    rid = "hist-1"
    reqledger.on_enqueue(rid)
    reqledger.on_admit(rid, replica="serve-r1")
    reqledger.on_decode(rid, n_lanes=1)
    reqledger.on_finish(rid, tokens=1)
    names = {r["name"] for r in observe.counters().snapshot()}
    for st in reqledger.STAGES:
        assert f"tdx.serve.stage_{st}_s" in names
    assert "tdx.serve.request_e2e_s" in names


# ---------------------------------------------------------------------------
# aggregation: tail report, occupancy ring, flight dumps
# ---------------------------------------------------------------------------


def test_tail_report_percentiles_and_p99_blame(ledger):
    """The fleet rollup: e2e percentiles, per-stage shares, and a p99
    blame breakdown whose shares sum to ~1 for the slow cohort."""
    for i in range(10):
        rid = f"tail-{i}"
        reqledger.on_enqueue(rid)
        if i == 9:
            time.sleep(0.01)   # one deliberately queue-bound straggler
        reqledger.on_admit(rid, replica="serve-r1")
        reqledger.on_decode(rid, n_lanes=1)
        reqledger.on_finish(rid, tokens=1)
    tail = reqledger.tail_report()
    assert tail["completed"] == 10
    assert tail["e2e_s"]["p99"] >= tail["e2e_s"]["p50"] > 0.0
    assert set(tail["stages"]) == set(reqledger.STAGES)
    blame = tail["p99_blame"]
    assert abs(sum(blame.values()) - 1.0) < 0.01
    # the straggler IS the p99 sample, and it waited in queue
    assert blame["queue"] > 0.5, blame


def test_occupancy_ring_and_gauge(ledger):
    reqledger.occupancy_sample(replica="serve-r1", decode_busy=1,
                               decode_lanes=2, kv_pages_free=7,
                               kv_pages_shared=3, prefix_hit_rate=0.25,
                               queue_depth=4)
    rep = reqledger.occupancy_report()
    assert rep["count"] == 1
    s = rep["samples"][0]
    assert (s["busy"], s["lanes"], s["free"], s["shared"], s["depth"]) \
        == (1, 2, 7, 3, 4)
    assert s["hit_rate"] == 0.25
    gauges = {r["name"]: r["value"]
              for r in observe.counters().snapshot() if r["type"] == "gauge"}
    assert gauges["tdx.serve.decode_occupancy"] == 0.5


def test_flight_dump_carries_ledger_snapshot(ledger, tmp_path):
    """A post-mortem bundle answers "who was in flight and where had
    their time gone": the dump gains a top-level ``ledger`` key with the
    tail report, live summaries, and occupancy samples."""
    reqledger.on_enqueue("fd-done")
    reqledger.on_admit("fd-done", replica="serve-r1")
    reqledger.on_finish("fd-done", tokens=1)
    reqledger.on_enqueue("fd-live")           # still in flight at dump time
    reqledger.occupancy_sample(decode_busy=1, decode_lanes=2)
    with tdx_config.override(flight_dir=str(tmp_path)):
        path = observe.flight_dump("ledger_test")
    assert path is not None
    doc = json.load(open(path))
    led = doc["ledger"]
    assert set(led) == {"tail", "live", "occupancy"}
    assert led["tail"]["finished"] == 1
    assert [r["rid"] for r in led["live"]] == ["fd-live"]
    assert len(led["occupancy"]) == 1


def test_reset_drops_everything(ledger):
    reqledger.on_enqueue("rst-1")
    reqledger.on_finish("rst-1")
    reqledger.occupancy_sample(decode_busy=1, decode_lanes=1)
    reqledger.reset()
    rep = reqledger.requests_report()
    assert rep["finished"] == 0 and not rep["recent"]
    assert reqledger.occupancy_report()["count"] == 0


# ---------------------------------------------------------------------------
# the HTTP plane: /requests and /tail
# ---------------------------------------------------------------------------


def _get(url: str, timeout: float = 10.0):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def test_http_requests_and_tail_endpoints(tmp_path):
    observe.stop_background()
    observe.reset()
    observe.enable(True)
    try:
        port_file = tmp_path / "obs.port"
        with tdx_config.override(obs_port=0, obs_port_file=str(port_file)):
            observe.counter("tdx.test.reqledger_http").inc()  # arm
            server = httpd.get_server()
            assert server is not None and server.is_alive()

            rid = "http-1"
            reqledger.on_enqueue(rid, priority=0, n_prompt=4)
            reqledger.on_admit(rid, replica="serve-r1", prefix_tokens=2)
            reqledger.on_decode(rid, n_lanes=2, replica="serve-r1")
            reqledger.on_finish(rid, tokens=3)

            status, body = _get(server.url("/"))
            assert status == 200
            idx = json.loads(body)["endpoints"]
            assert "/requests" in idx and "/tail" in idx

            status, body = _get(server.url("/requests"))
            assert status == 200
            doc = json.loads(body)
            assert doc["finished"] == 1
            assert doc["recent"][0]["rid"] == rid

            status, body = _get(server.url(f"/requests/{rid}"))
            assert status == 200
            detail = json.loads(body)
            assert detail["outcome"] == "ok"
            assert abs(_stage_sum(detail) - detail["e2e_s"]) < 1e-4
            assert _kinds(detail)[0] == "enqueue"

            assert _get(server.url("/requests/nope"))[0] == 404

            status, body = _get(server.url("/tail"))
            assert status == 200
            tail = json.loads(body)
            assert tail["completed"] == 1
            assert set(tail["p99_blame"]) == set(reqledger.STAGES)
    finally:
        observe.enable(None)
        observe.stop_background()
        observe.reset()


# ---------------------------------------------------------------------------
# the acceptance pin: hedged + chaos-killed + requeued, one flow, one
# coherent autopsy timeline (satellite: flow propagation tests)
# ---------------------------------------------------------------------------


def _check_oracle(fl, reqs, out):
    for r in reqs:
        want, _ = oracle_generate(
            fl.family, fl.cfg, fl.params, r.tokens, r.max_new_tokens,
            r.eos_id,
        )
        assert out[r.rid] == want, (r.rid, out[r.rid], want)


@pytest.mark.slow
def test_fleet_storm_hedge_kill_requeue_one_flow_and_autopsy(
        shared_cache, tmp_path):
    """A 2-replica storm with zero-threshold hedging and a chaos
    replica-kill: every finished request's stages sum to e2e, hedge and
    requeue instants across replicas share the request's ONE flow id,
    and ``tdx_trace.py autopsy`` reconstructs a hedged request as a
    single coherent timeline from the flushed trace."""
    gc = GuardrailConfig(breaker=False, brownout=False,
                         hedging=True, hedge_wait_frac=0.0)
    trace_dir = tmp_path / "traces"
    observe.enable(True)
    observe.reset()
    try:
        with tdx_config.override(cache_dir=shared_cache,
                                 trace_dir=str(trace_dir)):
            fl = ServeFleet(
                LLAMA, family="llama", serve_cfg=SCFG,
                fleet_cfg=FleetConfig(min_replicas=2, max_replicas=2,
                                      autoscale=False, stall_s=60.0,
                                      guardrails=gc),
            )
            with fl:
                fl.start(2, timeout=240.0)
                chaos.install("fleet@2=raise")
                try:
                    reqs = [
                        Request(f"lg{i}", [(5 * i + j) % 128
                                           for j in range(2 + i % 4)],
                                max_new_tokens=4 + (i % 3),
                                deadline_s=120.0, arrival_step=i)
                        for i in range(10)
                    ]
                    i = 0
                    deadline = time.monotonic() + 240.0
                    while i < len(reqs) or fl._pending:
                        while (i < len(reqs)
                               and reqs[i].arrival_step <= fl._tick_no):
                            fl.submit(reqs[i])
                            i += 1
                        fl.tick()
                        assert time.monotonic() < deadline, (
                            fl._pending, [h.state for h in fl.handles])
                        time.sleep(0.0005)
                finally:
                    chaos.clear()
                out = dict(fl.results)
                assert set(out) == {r.rid for r in reqs}
                assert not fl.rejected
                _check_oracle(fl, reqs, out)

                # every request attributed, stages sum to e2e
                hedged, retried, flows = [], [], {}
                for r in reqs:
                    summ = reqledger.summary(r.rid)
                    assert summ is not None and summ["outcome"] == "ok", r.rid
                    assert abs(_stage_sum(summ) - summ["e2e_s"]) < 5e-3, summ
                    flows[r.rid] = summ["flow"]
                    assert flows[r.rid] is not None
                    if summ["hedged"]:
                        hedged.append(r.rid)
                    if summ["attempts"] > 1:
                        retried.append(r.rid)
                assert hedged, "zero-threshold hedging never fired"
                assert retried, "the chaos kill requeued nothing"
                # flow ids are per-request unique (the join key is real)
                assert len(set(flows.values())) == len(flows)
        observe.flush(trace_dir=str(trace_dir))
    finally:
        observe.enable(None)
        observe.health.reset()

    # -- the flushed trace joins the story back together ------------------
    files = glob.glob(str(trace_dir / "*.json"))
    assert files
    events = []
    for fn in files:
        events.extend(json.load(open(fn))["traceEvents"])

    def instants(name, flow):
        return [e for e in events if e.get("ph") == "i"
                and e.get("name") == name
                and (e.get("args") or {}).get("flow") == flow]

    rid_h = hedged[0]
    assert instants("fleet.hedge", flows[rid_h]), \
        "fleet.hedge instant does not carry the request's flow id"
    for rid in retried:
        assert instants("fleet.requeue", flows[rid]), \
            f"fleet.requeue for {rid} does not carry its flow id"
    # terminal instants: one per request, flow-stamped, timeline aboard
    for r in reqs:
        term = [e for e in events if e.get("ph") == "i"
                and e.get("name") == "serve.request"
                and (e.get("args") or {}).get("rid") == r.rid]
        assert len(term) == 1, r.rid
        assert term[0]["args"]["flow"] == flows[r.rid]

    # a requeued request's timeline shows both replicas
    if set(hedged) & set(retried):
        rid_hr = sorted(set(hedged) & set(retried))[0]
        detail = next(e["args"] for e in events
                      if e.get("ph") == "i"
                      and e.get("name") == "serve.request"
                      and (e.get("args") or {}).get("rid") == rid_hr)
        admits = {ev.get("replica") for ev in detail["events"]
                  if ev["k"] == "admit"}
        assert len(admits) >= 2, detail["events"]

    # -- autopsy: one coherent reconstructed life --------------------------
    proc = subprocess.run(
        [sys.executable, CLI, "autopsy", rid_h, str(trace_dir)],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    rep = proc.stdout
    assert f"== autopsy: rid={rid_h}" in rep
    assert "attribution (stages sum to e2e by construction):" in rep
    for st in reqledger.STAGES:
        assert st in rep
    assert "timeline (" in rep
    assert "hedge" in rep

    proc = subprocess.run(
        [sys.executable, CLI, "autopsy", "no-such-rid", str(trace_dir)],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 2


@pytest.mark.slow
def test_fleet_spec_storm_kill_hedge_deadline_bitwise_and_clean_ledger(
        shared_cache):
    """ISSUE 19 acceptance: a spec-on fleet storm (speculation is the
    default) under a chaos replica kill, zero-threshold hedging, and one
    hopeless deadline — every completed output is bitwise-equal to the
    oracle, every finished request's stages still sum to e2e with the
    verify events folded into the decode stage, and no KV pages leak."""
    gc = GuardrailConfig(breaker=False, brownout=False,
                         hedging=True, hedge_wait_frac=0.0)
    observe.enable(True)
    observe.reset()
    try:
        with tdx_config.override(cache_dir=shared_cache):
            fl = ServeFleet(
                LLAMA, family="llama", serve_cfg=SCFG,
                fleet_cfg=FleetConfig(min_replicas=2, max_replicas=2,
                                      autoscale=False, stall_s=60.0,
                                      guardrails=gc),
            )
            with fl:
                fl.start(2, timeout=240.0)
                chaos.install("fleet@2=raise")
                try:
                    # One shared prompt: repeats teach every replica's
                    # drafter the chain, so speculation provably fires.
                    prompt = [9, 4, 1, 4, 9, 2]
                    reqs = [Request(f"sp{i}", list(prompt),
                                    max_new_tokens=4 + (i % 2),
                                    deadline_s=(0.001 if i == 7 else 120.0),
                                    arrival_step=i)
                            for i in range(8)]
                    # ~27 s alone; the kill→respawn→hedge storm runs
                    # ~10x slower when the full suite saturates the
                    # 1-CPU CI host, so the hang-catch budget is wide.
                    out = fl.run(reqs, max_seconds=480.0)
                finally:
                    chaos.clear()
                spec_ticks = sum(
                    h.engine.spec_verify_ticks for h in fl.handles
                    if h.engine is not None)
                spec_accepted = sum(
                    h.engine.spec_accepted for h in fl.handles
                    if h.engine is not None)
                assert spec_ticks > 0, "the storm never speculated"
                assert spec_accepted > 0, "repeats must accept drafts"
                for r in reqs:
                    if r.rid in out:
                        assert r.rid not in fl.rejected, r.rid
                        _check_oracle(fl, [r], out)
                        summ = reqledger.summary(r.rid)
                        assert summ is not None and \
                            summ["outcome"] == "ok", r.rid
                        assert abs(_stage_sum(summ) - summ["e2e_s"]) \
                            < 5e-3, summ
                    else:
                        assert fl.rejected[r.rid].reason == "deadline", r.rid
                # the verify events rode inside the decode stage
                spec_rids = [
                    r.rid for r in reqs if r.rid in out
                    and (reqledger.summary(r.rid) or {}).get("spec_ticks")]
                assert spec_rids, "no finished request carried speculation"
                detail = reqledger.summary(spec_rids[0])
                assert "verify" in _kinds(detail)
                assert detail["decode_s"] > 0.0
                assert detail["spec_drafted"] >= detail["spec_accepted"] > 0 \
                    or detail["spec_accepted"] == 0
                # no KV pages leak past the storm on the survivors (the
                # chaos-killed replica's engine froze mid-batch; its
                # requests were requeued, its pool is garbage by design)
                for h in fl.handles:
                    if (h.state == "serving" and h.engine is not None
                            and h.engine.k_pages is not None):
                        assert h.engine.kv.pages_in_use == \
                            h.engine.prefix.page_count(), h.idx
    finally:
        observe.enable(None)
        observe.health.reset()
