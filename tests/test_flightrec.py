"""Flight recorder (torchdistx_tpu.observe.flightrec): the crash ring is
independent of the tracer's export buffer, every failure trigger leaves a
schema-valid dump (chaos injection, watchdog kill, MaterializationError,
uncaught exception), dumps are throttled per reason, ``%h``/``%p`` path
templates expand, the CLI renders dumps and fleets, silent span loss is
counted — and the whole layer stays under the 2% train-step overhead
gate."""

from __future__ import annotations

import glob
import json
import os
import subprocess
import sys
import time

import pytest

import torchdistx_tpu.config as tdx_config
from torchdistx_tpu import observe
from torchdistx_tpu.observe import flightrec

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CLI = os.path.join(REPO, "tools", "tdx_trace.py")


@pytest.fixture()
def flight(tmp_path):
    """Armed flight recorder with a clean slate, disarmed after."""
    observe.reset()
    d = tmp_path / "flight"
    with tdx_config.override(flight_dir=str(d)):
        yield str(d)
    observe.reset()


def _dumps(d, reason=None):
    pat = f"flight-*-{flightrec._safe(reason)}.json" if reason else "flight-*.json"
    return sorted(glob.glob(os.path.join(d, pat)))


class TestRing:
    def test_ring_survives_tracer_drain(self, flight):
        with observe.span("pre.crash", category="t"):
            pass
        # A flush drains the tracer's export buffer...
        assert observe.tracer().drain()
        assert not observe.tracer().events
        # ...but the crash ring still holds the event, and the dump
        # carries it.
        path = observe.flight_dump("test_reason")
        doc = json.load(open(path))
        assert any(e.get("name") == "pre.crash" for e in doc["events"])

    def test_ring_is_bounded(self, flight):
        assert flightrec._ring.maxlen is not None

    def test_dropped_events_counted(self, flight):
        from torchdistx_tpu.observe.spans import Tracer

        t = Tracer(max_events=4)
        for i in range(10):
            t.instant(f"i{i}")
        assert t.dropped == 6
        snap = {r["name"]: r["value"] for r in observe.counters().snapshot()
                if r["type"] == "counter"}
        assert snap.get("tdx.observe.dropped_events", 0) >= 6

    def test_dropped_events_surface_in_summary(self, flight, tmp_path):
        from torchdistx_tpu.observe.spans import Tracer

        t = Tracer(max_events=2)
        for i in range(7):
            t.instant(f"i{i}")
        with observe.span("s"):
            pass
        d = tmp_path / "traces"
        observe.flush(trace_dir=str(d))
        out = subprocess.run(
            [sys.executable, CLI, "summary", str(d)],
            capture_output=True, text=True, timeout=60,
        )
        assert out.returncode == 0, out.stderr
        assert "dropped" in out.stdout

    def test_dump_includes_config_env_and_snapshots(self, flight):
        observe.counter("tdx.test.flightc").inc(5)
        doc = json.load(open(observe.flight_dump("test_reason")))
        assert not flightrec.validate(doc)
        assert doc["config"]["flight_dir"] == flight
        assert "python" in doc["env"]
        final = doc["counter_snapshots"][-1]["counters"]
        assert any(r["name"] == "tdx.test.flightc" and r["value"] == 5
                   for r in final)


class TestTriggers:
    def test_chaos_injection_dumps(self, flight):
        from torchdistx_tpu.chaos.inject import execute
        from torchdistx_tpu.chaos.plan import Fault

        execute(Fault(site="step", step=1, kind="slow", arg="0"))
        (path,) = _dumps(flight, "chaos_injected")
        doc = json.load(open(path))
        assert not flightrec.validate(doc)
        assert doc["context"]["spec"].startswith("step@1=slow")

    def test_materialization_error_dumps(self, flight):
        import torch

        from torchdistx_tpu import chaos
        from torchdistx_tpu.deferred_init import deferred_init
        from torchdistx_tpu.jax_bridge import (
            MaterializationError, materialize_module_jax,
        )
        from torchdistx_tpu.jax_bridge import materialize as mat

        chaos.clear()
        mat._reset_cache_binding()
        try:
            with tdx_config.override(
                flight_dir=flight, fault_plan="compile@1=raise x9",
                materialize_pipeline="off", materialize_retries=0,
            ):
                with pytest.raises(MaterializationError):
                    materialize_module_jax(
                        deferred_init(torch.nn.Linear, 8, 4)
                    )
        finally:
            chaos.clear()
            mat._reset_cache_binding()
        (path,) = _dumps(flight, "materialization_error")
        doc = json.load(open(path))
        assert not flightrec.validate(doc)
        assert doc["context"]["failed_groups"] == [0]

    def test_watchdog_kill_dumps_and_run_survives(self, flight):
        import torch

        from torchdistx_tpu import chaos
        from torchdistx_tpu.deferred_init import deferred_init
        from torchdistx_tpu.jax_bridge import materialize_module_jax
        from torchdistx_tpu.jax_bridge import materialize as mat

        chaos.clear()
        mat._reset_cache_binding()
        try:
            with tdx_config.override(
                flight_dir=flight, fault_plan="compile@1=hang:30",
                materialize_pipeline="off", compile_deadline_s=1.0,
            ):
                params = materialize_module_jax(
                    deferred_init(torch.nn.Linear, 8, 4)
                )
        finally:
            chaos.clear()
            mat._reset_cache_binding()
        assert set(params) == {"weight", "bias"}
        (path,) = _dumps(flight, "compile_watchdog_kill")
        doc = json.load(open(path))
        assert doc["context"]["stage"] == "compile"

    def test_unhandled_exception_dumps_in_subprocess(self, tmp_path):
        # stdlib-only child (observe imports no torch/jax): fast, and
        # proves the excepthook path works without the heavy stack.
        d = tmp_path / "fl"
        script = (
            "from torchdistx_tpu import observe\n"
            "observe.counter('tdx.t.arm').inc()\n"
            "raise RuntimeError('deliberate')\n"
        )
        r = subprocess.run(
            [sys.executable, "-c", script], capture_output=True, text=True,
            timeout=120, cwd=REPO,
            env={**os.environ, "TDX_FLIGHT_DIR": str(d),
                 "PYTHONPATH": REPO},
        )
        assert r.returncode != 0  # the exception still kills the process
        (path,) = _dumps(str(d), "unhandled_exception")
        doc = json.load(open(path))
        assert not flightrec.validate(doc)
        assert "RuntimeError: deliberate" in doc["context"]["error"]
        assert "Traceback" in doc["context"]["traceback"]

    def test_worker_thread_exception_dumps(self, tmp_path):
        # Subprocess: pytest's threadexception plugin swaps
        # threading.excepthook per-test, so the wrap can only be
        # observed in a clean interpreter.
        d = tmp_path / "fl"
        script = (
            "import threading\n"
            "from torchdistx_tpu import observe\n"
            "observe.counter('tdx.t.arm').inc()\n"
            "def boom():\n"
            "    raise ValueError('thread-boom')\n"
            "t = threading.Thread(target=boom, name='w-crash')\n"
            "t.start(); t.join()\n"
        )
        r = subprocess.run(
            [sys.executable, "-c", script], capture_output=True, text=True,
            timeout=120, cwd=REPO,
            env={**os.environ, "TDX_FLIGHT_DIR": str(d),
                 "PYTHONPATH": REPO},
        )
        assert r.returncode == 0  # a thread death doesn't kill the process
        (path,) = _dumps(str(d), "unhandled_exception")
        doc = json.load(open(path))
        assert "thread-boom" in doc["context"]["error"]
        assert doc["context"]["thread"] == "w-crash"

    def test_throttle_suppresses_repeats(self, flight):
        assert observe.flight_dump("hot_reason") is not None
        assert observe.flight_dump("hot_reason") is None  # inside interval
        assert observe.flight_dump("other_reason") is not None  # per-reason
        snap = {(r["name"], r.get("labels", {}).get("reason")): r["value"]
                for r in observe.counters().snapshot()
                if r["type"] == "counter"}
        assert snap.get(
            ("tdx.observe.flight_dumps_suppressed", "hot_reason"), 0
        ) == 1

    def test_unarmed_is_noop(self, tmp_path):
        observe.reset()
        assert not flightrec.armed()
        assert observe.flight_dump("anything") is None


class TestPathTemplates:
    def test_expand_tokens(self):
        import socket

        host = socket.gethostname().split(".")[0]
        assert tdx_config.expand_path("/x/%h/m-%p.prom") == \
            f"/x/{host}/m-{os.getpid()}.prom"
        assert tdx_config.expand_path("/plain/path") == "/plain/path"
        assert tdx_config.expand_path(None) is None

    def test_flight_dir_template(self, tmp_path):
        observe.reset()
        d = str(tmp_path / "logs" / "%h")
        with tdx_config.override(flight_dir=d):
            path = observe.flight_dump("templated")
        observe.reset()
        assert path is not None and "%h" not in path
        import socket

        assert socket.gethostname().split(".")[0] in path

    def test_metrics_path_template(self, tmp_path):
        observe.reset()
        observe.enable(True)
        try:
            observe.counter("tdx.t.m").inc()
            mp = str(tmp_path / "m-%p.jsonl")
            written = observe.flush(metrics_path=mp)
            assert written["metrics"].endswith(f"m-{os.getpid()}.jsonl")
            assert os.path.isfile(written["metrics"])
        finally:
            observe.enable(None)
            observe.reset()


class TestCLI:
    def _mk_host(self, root, name):
        d = root / name
        d.mkdir(parents=True)
        observe.reset()
        observe.enable(True)
        with observe.span("jax.compile", category="jax"):
            time.sleep(0.001)
        observe.counter("tdx.jax.compile_cache_hit").inc(2)
        observe.gauge("tdx.serve.slo.ttft_p50_s").set(0.012)
        observe.gauge("tdx.serve.slo.ttft_p95_s").set(0.040)
        observe.gauge("tdx.serve.slo.ttft_p99_s").set(0.080)
        with tdx_config.override(flight_dir=str(d)):
            observe.flight_dump("serve_fault", step=3)
        observe.flush(trace_dir=str(d))
        observe.enable(None)
        observe.reset()
        return d

    def test_flight_render(self, tmp_path):
        d = self._mk_host(tmp_path, "host-a")
        out = subprocess.run(
            [sys.executable, CLI, "flight", str(d)],
            capture_output=True, text=True, timeout=60,
        )
        assert out.returncode == 0, out.stderr
        assert "reason: serve_fault" in out.stdout
        assert "0 invalid" in out.stdout

    def test_flight_invalid_exit_code(self, tmp_path):
        bad = tmp_path / "flight-1-1-bad.json"
        bad.write_text(json.dumps({"schema": 99}))
        out = subprocess.run(
            [sys.executable, CLI, "flight", str(tmp_path)],
            capture_output=True, text=True, timeout=60,
        )
        assert out.returncode == 1
        assert "SCHEMA INVALID" in out.stdout

    def test_fleet_rollup(self, tmp_path):
        self._mk_host(tmp_path, "host-a")
        self._mk_host(tmp_path, "host-b")
        out = subprocess.run(
            [sys.executable, CLI, "fleet", str(tmp_path)],
            capture_output=True, text=True, timeout=60,
        )
        assert out.returncode == 0, out.stderr
        assert "fleet: 2 host(s)" in out.stdout
        assert "host-a" in out.stdout and "host-b" in out.stdout
        assert "serve_fault" in out.stdout
        assert "TTFT" in out.stdout  # per-host SLO digest

    def test_fleet_dedupes_counters_across_source_formats(self, tmp_path):
        # One host dir holding BOTH a .prom export and a flight dump
        # carrying the same labeled counter (the obs-smoke layout):
        # the two spellings must canonicalize to one stream, not sum.
        host = tmp_path / "hostA"
        host.mkdir()
        (host / "metrics.prom").write_text(
            'tdx_chaos_injected{kind="raise"} 3\n')
        doc = {
            "schema": 1, "reason": "chaos_injected", "time": 1.0,
            "pid": 1, "host": "hostA", "events": [], "config": {},
            "env": {}, "counter_snapshots": [{"ts": 1.0, "counters": [
                {"name": "tdx.chaos.injected", "labels": {"kind": "raise"},
                 "type": "counter", "value": 3}]}],
        }
        (host / "flight-1-001-chaos_injected.json").write_text(
            json.dumps(doc))
        out = subprocess.run(
            [sys.executable, CLI, "fleet", str(tmp_path)],
            capture_output=True, text=True, timeout=60,
        )
        assert out.returncode == 0, out.stderr
        # First hostA line is the table row (a second appears in the
        # dumps-by-reason section).
        row = next(l for l in out.stdout.splitlines()
                   if l.strip().startswith("hostA"))
        assert row.split()[-2] == "3", row  # chaos column: 3, not 6

    def test_fleet_aggregates_per_pid_metrics_files(self, tmp_path):
        # %p templating puts one file per process in a host dir:
        # counters sum across pids, singleton gauges take max.
        import importlib.util

        host = tmp_path / "hostA"
        host.mkdir()
        for pid in (111, 222):
            (host / f"m-{pid}.prom").write_text(
                "# TYPE tdx_jax_compile_cache_hit counter\n"
                "tdx_jax_compile_cache_hit 2\n"
                "# TYPE tdx_jax_link_bandwidth_gbps gauge\n"
                "tdx_jax_link_bandwidth_gbps 2.5\n"
                "# TYPE tdx_serve_tokens_per_s gauge\n"
                "tdx_serve_tokens_per_s 100\n")
        spec = importlib.util.spec_from_file_location("_tdx_trace", CLI)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        counters = mod._load_metrics_files(str(host))
        assert counters["tdx_jax_compile_cache_hit"] == 4
        assert counters["tdx_jax_link_bandwidth_gbps"] == 2.5  # max
        assert counters["tdx_serve_tokens_per_s"] == 200  # per-replica sum

    def test_flight_finds_dumps_recursively(self, tmp_path):
        deep = tmp_path / "run-3" / "host-7"
        deep.mkdir(parents=True)
        observe.reset()
        with tdx_config.override(flight_dir=str(deep)):
            observe.flight_dump("serve_fault")
        observe.reset()
        out = subprocess.run(
            [sys.executable, CLI, "flight", str(tmp_path)],
            capture_output=True, text=True, timeout=60,
        )
        assert out.returncode == 0, out.stderr
        assert "serve_fault" in out.stdout

    def test_summary_slo_digest(self, tmp_path):
        d = self._mk_host(tmp_path, "host-a")
        out = subprocess.run(
            [sys.executable, CLI, "summary", str(d)],
            capture_output=True, text=True, timeout=60,
        )
        assert out.returncode == 0, out.stderr
        assert "serve SLOs" in out.stdout
        assert "p99=80.0ms" in out.stdout
        assert "flight-recorder dumps: 1" in out.stdout


class TestOverheadGate:
    def test_train_step_overhead_under_2pct(self, tmp_path):
        """The acceptance gate: with telemetry enabled AND the flight
        recorder armed, the recorder's per-step cost stays under 2% of
        a representative train step.

        Methodology: a whole-loop A/B on this 1-core CI box drowns a
        sub-1% effect in ±5% scheduler noise, so the gate measures the
        two quantities separately, each repeat-and-min (stable), and
        compares them: (a) the FULL per-step telemetry cost — meter
        span + derived gauges + ring tee, i.e. every instruction the
        armed recorder adds to a step — measured around an
        already-resident result; (b) a real jitted step's device time.
        Both sides measured, nothing estimated."""
        import jax
        import jax.numpy as jnp

        x = jax.random.normal(jax.random.PRNGKey(0), (384, 384), jnp.float32)

        @jax.jit
        def step(x):
            return x @ x / 384.0

        ready = step(x)
        ready.block_until_ready()
        # (b) representative step time: repeat-and-min of an 8-matmul
        # chain (single-digit ms on this box — the SMALL end of real
        # train steps, so the gate is conservative).
        step_times = []
        for _ in range(7):
            t0 = time.perf_counter()
            out = x
            for _ in range(8):
                out = step(out)
            out.block_until_ready()
            step_times.append(time.perf_counter() - t0)
        t_step = min(step_times)

        # (a) full armed-recorder per-step cost.
        observe.reset()
        observe.enable(True)
        try:
            with tdx_config.override(flight_dir=str(tmp_path / "fl")):
                meter = observe.StepMeter(
                    tokens_per_step=1024, flops_per_step=1e9,
                    peak_tflops=100.0,
                )
                for _ in range(20):  # warm handles, arm the ring tee
                    meter.start()
                    meter.stop(ready)
                pair_times = []
                for _ in range(5):
                    n = 200
                    t0 = time.perf_counter()
                    for _ in range(n):
                        meter.start()
                        meter.stop(ready)
                    pair_times.append((time.perf_counter() - t0) / n)
        finally:
            observe.enable(None)
            observe.reset()
        t_meter = min(pair_times)
        overhead = t_meter / t_step
        assert overhead < 0.02, (
            f"armed recorder costs {t_meter * 1e6:.1f}µs/step = "
            f"{overhead:.2%} of a {t_step * 1e3:.2f}ms step"
        )
        # Absolute backstop: the per-step cost must stay tens of µs —
        # a 10ms step budget must never be eaten by telemetry.
        assert t_meter < 200e-6, f"{t_meter * 1e6:.1f}µs/step"
