"""Failure detection + elastic checkpoint-restart recovery, and the
runtime config surface (SURVEY.md §5 rows the reference lacks)."""

import jax
import jax.numpy as jnp
import pytest

import torchdistx_tpu.config as tdx_config
from torchdistx_tpu.utils.failures import (
    FailureDetector,
    device_health,
    run_elastic,
)


class TestDeviceHealth:
    def test_all_healthy(self):
        report = device_health()
        assert report["healthy"]
        assert len(report["devices"]) == len(jax.devices())
        assert all(e["ok"] and e["latency_ms"] is not None for e in report["devices"])

    def test_detector_threshold_and_recovery(self, monkeypatch):
        calls = []
        det = FailureDetector(threshold=2, on_failure=lambda r: calls.append(r))
        healthy = {"healthy": True, "devices": []}
        sick = {"healthy": False, "devices": [{"ok": False}]}
        seq = iter([sick, sick, sick, healthy, sick])
        monkeypatch.setattr(
            "torchdistx_tpu.utils.failures.device_health",
            lambda devices=None: next(seq),
        )
        assert det.check() is False
        assert not calls  # below threshold
        assert det.check() is False
        assert len(calls) == 1  # fired exactly once at the threshold
        assert det.check() is False
        assert len(calls) == 1  # no refire while still down
        assert det.check() is True  # recovered; counter resets
        assert det.check() is False
        assert det.consecutive_failures == 1


class _Boom(RuntimeError):
    pass


class TestRunElastic:
    def _step(self, fail_at):
        seen = {"n": 0}

        def step(state, batch):
            seen["n"] += 1
            if seen["n"] in fail_at:
                raise _Boom(f"injected at call {seen['n']}")
            return {"x": state["x"] + batch}, {"loss": float(state["x"])}

        return step

    def test_recovers_from_injected_failure(self, tmp_path):
        state = {"x": jnp.float32(0.0)}
        batches = [jnp.float32(i) for i in range(1, 7)]
        # fail on the 4th call; checkpoint every 2 steps
        step = self._step(fail_at={4})
        out, steps, restarts = run_elastic(
            step, state, batches,
            checkpoint_dir=str(tmp_path), checkpoint_every=2,
            retry_on=(_Boom,), max_restarts=2,
        )
        assert steps == 6
        assert restarts == 1
        # replay is deterministic: sum 1..6 regardless of the restart
        assert float(out["x"]) == 21.0

    def test_budget_exhaustion_reraises(self, tmp_path):
        state = {"x": jnp.float32(0.0)}
        step = self._step(fail_at={2, 3, 4, 5, 6, 7, 8, 9})
        with pytest.raises(_Boom):
            run_elastic(
                step, state, [jnp.float32(1.0)] * 5,
                checkpoint_dir=str(tmp_path), checkpoint_every=1,
                retry_on=(_Boom,), max_restarts=2,
            )

    def test_unlisted_exception_fails_fast(self, tmp_path):
        def step(state, batch):
            raise ValueError("a real bug, not a device failure")

        with pytest.raises(ValueError):
            run_elastic(
                step, {"x": jnp.float32(0.0)}, [jnp.float32(1.0)],
                checkpoint_dir=str(tmp_path), retry_on=(_Boom,),
            )

    def test_no_checkpoint_dir_raises_on_failure(self):
        step = self._step(fail_at={1})
        with pytest.raises(RuntimeError, match="no checkpoint"):
            run_elastic(
                step, {"x": jnp.float32(0.0)}, [jnp.float32(1.0)], retry_on=(_Boom,)
            )


class TestDeviceHealthDeadline:
    def test_wedged_device_probe_is_bounded(self, monkeypatch):
        # A wedged device accepts work and never completes it: make
        # device_put hang and check the probe reports a timeout instead
        # of hanging in exactly the state it exists to detect.
        import threading
        import time as _time

        import torchdistx_tpu.utils.failures as F

        real_put = jax.device_put

        def wedged_put(x, d):
            _time.sleep(2.0)
            return real_put(x, d)

        monkeypatch.setattr(jax, "device_put", wedged_put)
        try:
            t0 = _time.perf_counter()
            report = device_health(devices=[jax.devices()[0]], deadline=0.3)
            assert _time.perf_counter() - t0 < 5.0
            assert not report["healthy"]
            assert "timed out" in report["devices"][0]["error"]

            # Polling again while the probe is still wedged must NOT
            # stack another doomed thread — unhealthy, immediately.
            n_before = threading.active_count()
            report2 = device_health(devices=[jax.devices()[0]], deadline=0.3)
            assert not report2["healthy"]
            assert "still wedged" in report2["devices"][0]["error"]
            assert threading.active_count() <= n_before
        finally:
            F._STUCK_PROBES.clear()  # don't poison later device_health users

    def test_deadline_none_keeps_inline_probing(self):
        report = device_health(deadline=None)
        assert report["healthy"]


class TestBackoff:
    def test_backoff_schedule_respected(self, tmp_path, monkeypatch):
        import torchdistx_tpu.utils.failures as F

        sleeps = []
        monkeypatch.setattr(F.time, "sleep", lambda s: sleeps.append(s))
        step = TestRunElastic()._step(fail_at={2, 3, 4})
        out, steps, restarts = run_elastic(
            step, {"x": jnp.float32(0.0)}, [jnp.float32(1.0)] * 4,
            checkpoint_dir=str(tmp_path), checkpoint_every=1,
            retry_on=(_Boom,), max_restarts=5, probe_on_restart=False,
            backoff_base=0.2, backoff_max=0.5,
        )
        assert (steps, restarts) == (4, 3)
        # min(backoff_max, base * 2**(n-1)) for restarts 1..3.
        assert sleeps == pytest.approx([0.2, 0.4, 0.5])


class TestVerifyThenPrune:
    def test_prune_never_deletes_newest_verified(self, tmp_path, monkeypatch):
        # A save whose verification fails must be quarantined WITHOUT
        # pruning the older good checkpoints — prune is strictly
        # verify-then-prune, and .corrupt dirs don't count toward (or
        # get deleted by) the keep budget.
        import torchdistx_tpu.utils.checkpoint as C

        real_verify = C.verify_checkpoint

        def flaky_verify(path):
            if str(path).rstrip("/").endswith("step_4"):
                return False, "synthetic verification failure"
            return real_verify(path)

        monkeypatch.setattr(C, "verify_checkpoint", flaky_verify)

        seen = {}

        def on_metrics(step, _m):
            if step == 5:
                seen["step2_survives"] = (tmp_path / "step_2").is_dir()
                seen["step4_quarantined"] = (tmp_path / "step_4.corrupt").is_dir()

        def step(state, batch):
            return {"x": state["x"] + batch}, {}

        out, steps, _ = run_elastic(
            step, {"x": jnp.float32(0.0)}, [jnp.float32(1.0)] * 6,
            checkpoint_dir=str(tmp_path), checkpoint_every=2,
            max_to_keep=1, probe_on_restart=False, on_metrics=on_metrics,
        )
        assert steps == 6
        # After the bad step-4 save, step_2 remained the newest verified
        # checkpoint and was NOT pruned despite max_to_keep=1.
        assert seen == {"step2_survives": True, "step4_quarantined": True}
        import os

        names = sorted(os.listdir(tmp_path))
        assert "step_6" in names            # newest verified
        assert "step_4.corrupt" in names    # quarantined, never pruned
        assert "step_2" not in names        # pruned only after 6 verified


class TestConfig:
    def test_defaults_from_env(self):
        cfg = tdx_config.get()
        assert isinstance(cfg.native, bool)
        assert cfg.rng_chunk_elems > 0

    def test_override_scoped_and_nested(self):
        base = tdx_config.get().rng_chunk_elems
        with tdx_config.override(rng_chunk_elems=42):
            assert tdx_config.get().rng_chunk_elems == 42
            with tdx_config.override(native=False):
                assert tdx_config.get().rng_chunk_elems == 42
                assert tdx_config.get().native is False
            assert tdx_config.get().rng_chunk_elems == 42
        assert tdx_config.get().rng_chunk_elems == base

    def test_override_disables_native_walks(self):
        import torch

        from torchdistx_tpu import _native
        from torchdistx_tpu._graph import CONTEXT_KEY
        from torchdistx_tpu.deferred_init import deferred_init, materialize_tensor
        from torchdistx_tpu.fake import get_fake_context

        with tdx_config.override(native=False):
            assert not _native.available()
            t = deferred_init(lambda: torch.ones(3) * 2)
            ctx = get_fake_context(t, CONTEXT_KEY)
            assert ctx.node._ng is None  # recorded without a native mirror
            assert torch.equal(materialize_tensor(t), torch.full((3,), 2.0))

    def test_set_flags_process_wide(self):
        before = tdx_config.get().log_level
        try:
            tdx_config.set_flags(log_level="DEBUG")
            assert tdx_config.get().log_level == "DEBUG"
        finally:
            tdx_config.set_flags(log_level=before)


class TestRunElasticAsync:
    def test_async_checkpoints_recover(self, tmp_path):
        import jax.numpy as jnp

        calls = {"n": 0}

        def step(state, batch):
            calls["n"] += 1
            if calls["n"] == 4:
                raise _Boom("injected")
            return {"x": state["x"] + batch}, {}

        out, steps, restarts = run_elastic(
            step, {"x": jnp.float32(0.0)}, [jnp.float32(i) for i in range(1, 7)],
            checkpoint_dir=str(tmp_path), checkpoint_every=2,
            retry_on=(_Boom,), max_restarts=2, async_checkpoints=True,
        )
        assert (steps, restarts) == (6, 1)
        assert float(out["x"]) == 21.0


class TestCrossProcessResume:
    def test_resume_from_previous_run(self, tmp_path):
        import jax.numpy as jnp

        # "Process 1" dies (budget exhausted) partway through.
        calls = {"n": 0}

        def flaky(state, batch):
            calls["n"] += 1
            if calls["n"] >= 4:
                raise _Boom("preempted")
            return {"x": state["x"] + batch}, {}

        batches = [jnp.float32(i) for i in range(1, 7)]
        with pytest.raises(_Boom):
            run_elastic(
                flaky, {"x": jnp.float32(0.0)}, batches,
                checkpoint_dir=str(tmp_path), checkpoint_every=2,
                retry_on=(_Boom,), max_restarts=0,
            )

        # "Process 2": fresh invocation, resume=True picks up step_2 on
        # disk and completes the remaining steps.
        def step(state, batch):
            return {"x": state["x"] + batch}, {}

        out, steps, restarts = run_elastic(
            step, {"x": jnp.float32(0.0)}, batches,
            checkpoint_dir=str(tmp_path), checkpoint_every=2,
            retry_on=(_Boom,), resume=True,
        )
        assert steps == 6
        assert float(out["x"]) == 21.0  # deterministic: sum 1..6

    def test_max_to_keep_prunes(self, tmp_path):
        import os

        import jax.numpy as jnp

        def step(state, batch):
            return {"x": state["x"] + batch}, {}

        run_elastic(
            step, {"x": jnp.float32(0.0)}, [jnp.float32(1.0)] * 8,
            checkpoint_dir=str(tmp_path), checkpoint_every=2,
            max_to_keep=2,
        )
        steps_on_disk = sorted(
            int(n.split("_")[1]) for n in os.listdir(tmp_path)
            if n.startswith("step_")
        )
        assert steps_on_disk == [6, 8]

    def test_resume_empty_dir_starts_fresh(self, tmp_path):
        import jax.numpy as jnp

        def step(state, batch):
            return {"x": state["x"] + batch}, {}

        out, steps, _ = run_elastic(
            step, {"x": jnp.float32(0.0)}, [jnp.float32(2.0)] * 3,
            checkpoint_dir=str(tmp_path), resume=True,
        )
        assert steps == 3 and float(out["x"]) == 6.0

    def test_max_to_keep_zero_rejected(self, tmp_path):
        import jax.numpy as jnp

        with pytest.raises(ValueError, match="max_to_keep"):
            run_elastic(
                lambda s, b: (s, {}), {"x": jnp.float32(0.0)},
                [jnp.float32(1.0)], checkpoint_dir=str(tmp_path),
                max_to_keep=0,
            )

    def test_max_to_keep_prunes_async(self, tmp_path):
        import os

        import jax.numpy as jnp

        def step(state, batch):
            return {"x": state["x"] + batch}, {}

        run_elastic(
            step, {"x": jnp.float32(0.0)}, [jnp.float32(1.0)] * 8,
            checkpoint_dir=str(tmp_path), checkpoint_every=2,
            max_to_keep=2, async_checkpoints=True,
        )
        steps_on_disk = sorted(
            int(n.split("_")[1]) for n in os.listdir(tmp_path)
            if n.startswith("step_")
        )
        assert steps_on_disk == [6, 8]


class TestDrainIntegrity:
    """ROADMAP open item: a drain checkpoint that fails verification was
    quarantined — yet CLEAN_EXIT.json was still written and exit_on_drain
    still exited 0, breaking the lossless-resume contract.  The drain
    must refuse the clean-exit promise it cannot keep."""

    def _run_with_corrupt_drain_save(self, tmp_path, monkeypatch,
                                     **elastic_kw):
        import os
        import signal

        import jax.numpy as jnp

        from torchdistx_tpu.utils import checkpoint as ckpt

        real_verify = ckpt.verify_checkpoint

        def corrupt_step3_verify(path, **kw):
            if os.path.basename(str(path)) == "step_3":
                return False, "injected drain corruption"
            return real_verify(path, **kw)

        monkeypatch.setattr(ckpt, "verify_checkpoint", corrupt_step3_verify)

        def step(state, batch):
            if int(batch) == 3:  # the announced preemption notice
                os.kill(os.getpid(), signal.SIGTERM)
            return {"x": state["x"] + batch}, {}

        return run_elastic(
            step, {"x": jnp.float32(0.0)},
            [jnp.float32(i) for i in range(1, 7)],
            checkpoint_dir=str(tmp_path), checkpoint_every=100,
            probe_on_restart=False, **elastic_kw,
        )

    def test_corrupt_drain_save_blocks_clean_exit_marker(
        self, tmp_path, monkeypatch
    ):
        from torchdistx_tpu import observe
        from torchdistx_tpu.utils.failures import CLEAN_EXIT_MARKER

        before = observe.counters().counter("tdx.elastic.drain_failures").value
        out, steps, _ = self._run_with_corrupt_drain_save(
            tmp_path, monkeypatch
        )
        assert steps == 3  # drained after finishing the step
        # The quarantined drain save must NOT advertise a clean exit.
        assert not (tmp_path / CLEAN_EXIT_MARKER).exists()
        assert (tmp_path / "step_3.corrupt").is_dir()
        assert observe.counters().counter(
            "tdx.elastic.drain_failures").value == before + 1

        # Resume falls back to the previous VERIFIED checkpoint (step_0)
        # and replays to completion bit-exactly.
        import jax.numpy as jnp
        import numpy as np

        out2, steps2, _ = run_elastic(
            lambda s, b: ({"x": s["x"] + b}, {}),
            {"x": jnp.float32(0.0)},
            [jnp.float32(i) for i in range(1, 7)],
            checkpoint_dir=str(tmp_path), checkpoint_every=100,
            resume=True, probe_on_restart=False,
        )
        assert steps2 == 6
        assert float(out2["x"]) == float(np.float32(sum(range(1, 7))))

    def test_corrupt_drain_save_exits_nonzero(self, tmp_path, monkeypatch):
        with pytest.raises(SystemExit) as ei:
            self._run_with_corrupt_drain_save(
                tmp_path, monkeypatch, exit_on_drain=True
            )
        assert ei.value.code == 1  # NOT the relauncher's resume signal


class TestStuckProbeLocking:
    def test_concurrent_health_checks_race_free(self, monkeypatch):
        """ROADMAP open item: _STUCK_PROBES was mutated without a lock
        although device_health is documented for concurrent
        FailureDetector use.  N concurrent checks against a wedged
        device must each report unhealthy and register at most ONE
        abandoned probe per device."""
        import threading
        import time as _time

        import torchdistx_tpu.utils.failures as F

        real_put = jax.device_put

        def wedged_put(x, d):
            _time.sleep(1.2)
            return real_put(x, d)

        monkeypatch.setattr(jax, "device_put", wedged_put)
        reports = []

        def check():
            reports.append(F.device_health(deadline=0.15))

        threads = [threading.Thread(target=check) for _ in range(4)]
        try:
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert len(reports) == 4
            assert all(not r["healthy"] for r in reports)
            with F._stuck_probes_lock:
                per_device = dict(F._STUCK_PROBES)
            assert set(per_device) <= {d.id for d in jax.devices()}
            # THE invariant: one abandoned probe thread per wedged
            # device, not one per concurrent caller — the per-device
            # probe lock serializes check→probe→register, so callers
            # 2..4 see the stuck entry instead of spawning their own.
            probes = [t for t in threading.enumerate()
                      if t.name.startswith("tdx-health-probe-")]
            assert len(probes) <= len(jax.devices())
            names = [t.name for t in probes]
            assert len(names) == len(set(names))  # no duplicate device
        finally:
            monkeypatch.undo()
            deadline = _time.time() + 5.0
            while F._STUCK_PROBES and _time.time() < deadline:
                F.device_health(deadline=2.0)  # healthy probe clears entries
                _time.sleep(0.05)
