"""Unified runtime telemetry (torchdistx_tpu.observe).

Covers the subsystem itself (span nesting, thread safety, counter
aggregation, Chrome-trace / Prometheus / JSON-lines export round-trips),
its activation knobs (TDX_TRACE_DIR / override(trace_dir=...)), the
tier-1 end-to-end contract — a CPU ``materialize_module_jax`` run emits
record/compile/materialize spans and compile-cache hit/miss counters;
a train loop emits per-step spans with throughput gauges — and the
``tools/tdx_trace.py`` summary CLI.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

import pytest

from torchdistx_tpu import observe
import torchdistx_tpu.config as tdx_config

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def telemetry():
    """Force telemetry on with a clean slate; restore config-driven
    gating (and drop collected events) afterwards so other tests keep
    the zero-overhead disabled path."""
    observe.reset()
    observe.enable(True)
    try:
        yield observe
    finally:
        observe.enable(None)
        observe.reset()


class TestSpans:
    def test_nesting_and_self_time(self, telemetry):
        with observe.span("outer", category="t"):
            time.sleep(0.02)
            with observe.span("inner", category="t"):
                time.sleep(0.01)
        by_name = {e["name"]: e for e in observe.tracer().events}
        outer, inner = by_name["outer"], by_name["inner"]
        assert outer["ph"] == inner["ph"] == "X"
        # containment: inner starts after outer, ends before it
        assert inner["ts"] >= outer["ts"]
        assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e3
        # outer's self-time excludes inner's duration
        assert outer["args"]["self_us"] <= outer["dur"] - inner["dur"] + 1e3

    def test_attrs_and_exception_annotation(self, telemetry):
        with pytest.raises(ValueError):
            with observe.span("boom", category="t", a=1) as sp:
                sp.set(b=2)
                raise ValueError("x")
        (ev,) = observe.tracer().events
        assert ev["args"]["a"] == 1 and ev["args"]["b"] == 2
        assert ev["args"]["error"] == "ValueError"

    def test_disabled_is_noop_singleton(self):
        observe.enable(False)
        try:
            n0 = len(observe.tracer().events)
            s1 = observe.span("a")
            s2 = observe.span("b")
            assert s1 is s2  # shared no-op object: zero allocation
            with s1:
                pass
            assert len(observe.tracer().events) == n0
        finally:
            observe.enable(None)

    def test_thread_safety(self, telemetry):
        barrier = threading.Barrier(4)  # all alive at once: distinct idents

        def worker(i):
            barrier.wait()
            for j in range(25):
                with observe.span(f"t{i}", category="thr"):
                    pass

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        events = [e for e in observe.tracer().events if e["ph"] == "X"]
        assert len(events) == 100
        assert len({e["tid"] for e in events}) == 4  # per-thread lanes

    def test_config_activation_scoped(self, tmp_path):
        observe.reset()
        assert not observe.enabled()
        with tdx_config.override(trace_dir=str(tmp_path)):
            assert observe.enabled()
            with observe.span("scoped"):
                pass
        assert not observe.enabled()
        assert any(e["name"] == "scoped" for e in observe.tracer().events)
        observe.reset()

    def test_env_var_resolution(self, monkeypatch, tmp_path):
        monkeypatch.setenv("TDX_TRACE_DIR", str(tmp_path))
        monkeypatch.setenv("TDX_METRICS_PATH", str(tmp_path / "m.prom"))
        cfg = tdx_config._from_env()
        assert cfg.trace_dir == str(tmp_path)
        assert cfg.metrics_path == str(tmp_path / "m.prom")


class TestCounters:
    def test_counter_aggregation_across_threads(self, telemetry):
        c = observe.counter("tdx.test.hits")

        def worker():
            for _ in range(1000):
                c.inc()

        threads = [threading.Thread(target=worker) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.value == 4000

    def test_gauge_and_histogram(self, telemetry):
        observe.gauge("tdx.test.g").set(1.5)
        observe.gauge("tdx.test.g").set(2.5)  # same handle, last wins
        h = observe.histogram("tdx.test.h", buckets=(0.1, 1.0))
        for v in (0.05, 0.5, 5.0):
            h.observe(v)
        snap = {r["name"]: r for r in observe.counters().snapshot()}
        assert snap["tdx.test.g"]["value"] == 2.5
        hr = snap["tdx.test.h"]
        assert hr["count"] == 3 and hr["min"] == 0.05 and hr["max"] == 5.0
        assert hr["buckets"] == {"0.1": 1, "1.0": 1, "+Inf": 1}
        # gauge sets also produce chrome counter samples (time series)
        samples = [e for e in observe.tracer().events if e["ph"] == "C"]
        assert [s["args"]["value"] for s in samples] == [1.5, 2.5]

    def test_labels_and_type_conflicts(self, telemetry):
        observe.counter("tdx.test.labeled", kind="a").inc()
        observe.counter("tdx.test.labeled", kind="b").inc(2)
        snap = [r for r in observe.counters().snapshot()
                if r["name"] == "tdx.test.labeled"]
        assert {r["labels"]["kind"]: r["value"] for r in snap} == {"a": 1, "b": 2}
        with pytest.raises(TypeError):
            observe.gauge("tdx.test.labeled", kind="a")


class TestExport:
    def test_chrome_trace_roundtrip(self, telemetry, tmp_path):
        with observe.span("phase", category="x", foo="bar"):
            pass
        observe.counter("tdx.c").inc(7)
        written = observe.flush(trace_dir=str(tmp_path))
        doc = json.load(open(written["trace"]))
        evs = doc["traceEvents"]
        span_ev = next(e for e in evs if e.get("ph") == "X")
        assert span_ev["name"] == "phase" and span_ev["args"]["foo"] == "bar"
        assert {"ts", "dur", "pid", "tid", "cat"} <= set(span_ev)
        counter_ev = next(e for e in evs if e.get("ph") == "C")
        assert counter_ev["args"]["value"] == 7
        assert any(e.get("ph") == "M" for e in evs)  # process metadata

    def test_prometheus_roundtrip(self, telemetry, tmp_path):
        observe.counter("tdx.x.total").inc(3)
        observe.gauge("tdx.x.gbps").set(1.25)
        observe.histogram("tdx.x.lat", buckets=(1.0,)).observe(0.5)
        path = tmp_path / "metrics.prom"
        observe.flush(metrics_path=str(path))
        text = path.read_text()
        assert "# TYPE tdx_x_total counter" in text
        assert "tdx_x_total 3" in text
        assert "tdx_x_gbps 1.25" in text
        assert 'tdx_x_lat_bucket{le="1.0"} 1' in text
        assert "tdx_x_lat_count 1" in text

    def test_labeled_counters_stay_distinct_in_trace(self, telemetry, tmp_path):
        observe.counter("tdx.graph.verify_failures", kind="a").inc(5)
        observe.counter("tdx.graph.verify_failures", kind="b").inc(3)
        written = observe.flush(trace_dir=str(tmp_path))
        doc = json.load(open(written["trace"]))
        samples = [e for e in doc["traceEvents"] if e.get("ph") == "C"
                   and e["name"].startswith("tdx.graph.verify_failures")]
        # two distinct counter streams, not one last-write-wins collision
        assert sorted(e["args"]["value"] for e in samples) == [3, 5]

    def test_prometheus_single_type_line_per_name(self, telemetry, tmp_path):
        observe.counter("tdx.z.fail", kind="a").inc()
        observe.counter("tdx.z.fail", kind="b").inc()
        text = observe.counters().to_prometheus()
        assert text.count("# TYPE tdx_z_fail counter") == 1
        assert 'tdx_z_fail{kind="a"} 1' in text
        assert 'tdx_z_fail{kind="b"} 1' in text

    def test_flush_drains_and_dedups(self, telemetry, tmp_path):
        with observe.span("once"):
            pass
        observe.counter("tdx.w").inc()
        d = tmp_path / "t"
        mp = tmp_path / "m.jsonl"
        assert observe.flush(trace_dir=str(d), metrics_path=str(mp))
        # nothing new since: no second trace file, no duplicate jsonl rows
        assert observe.flush(trace_dir=str(d), metrics_path=str(mp)) == {}
        assert len(list(d.iterdir())) == 1
        assert len(mp.read_text().splitlines()) == 1
        # spans were drained into the first file, not re-exported
        with observe.span("twice"):
            pass
        w2 = observe.flush(trace_dir=str(d))
        doc = json.load(open(w2["trace"]))
        span_names = [e["name"] for e in doc["traceEvents"] if e.get("ph") == "X"]
        assert span_names == ["twice"]

    def test_jsonl_metrics_roundtrip(self, telemetry, tmp_path):
        observe.counter("tdx.y").inc()
        path = tmp_path / "metrics.jsonl"
        observe.flush(metrics_path=str(path))
        recs = [json.loads(line) for line in path.read_text().splitlines()]
        assert any(r["name"] == "tdx.y" and r["value"] == 1 for r in recs)

    def test_jsonl_sink_supersedes_metrics(self, tmp_path):
        sink = observe.JsonlSink(str(tmp_path / "s.jsonl"))
        sink.log(step=1, loss=1.5, note=object())
        sink.close()
        (rec,) = [json.loads(line)
                  for line in (tmp_path / "s.jsonl").read_text().splitlines()]
        assert rec["step"] == 1 and rec["loss"] == 1.5
        assert isinstance(rec["note"], str)  # non-floats stringified

    def test_legacy_shims_warn_but_work(self, tmp_path):
        from torchdistx_tpu.utils import Metrics, StepTimer

        with pytest.warns(DeprecationWarning):
            m = Metrics(tmp_path / "legacy.jsonl")
        m.log(3, loss=0.5)
        m.close()
        (rec,) = [json.loads(line)
                  for line in (tmp_path / "legacy.jsonl").read_text().splitlines()]
        assert rec["step"] == 3 and rec["loss"] == 0.5
        with pytest.warns(DeprecationWarning):
            st = StepTimer()
        st.start()
        st.stop()
        assert st.steps == 1 and st.mean > 0


class TestStepMeter:
    def test_derived_gauges(self, telemetry):
        meter = observe.StepMeter(tokens_per_step=1000, flops_per_step=1e9,
                                  peak_tflops=100.0)
        meter.start()
        time.sleep(0.01)
        meter.stop()
        assert meter.steps == 1
        snap = {r["name"]: r["value"] for r in observe.counters().snapshot()}
        assert snap["tdx.train.tokens_per_s"] > 0
        assert snap["tdx.train.mfu_est"] > 0
        (ev,) = [e for e in observe.tracer().events if e["ph"] == "X"]
        assert ev["name"] == "train.step" and "tokens_per_s" in ev["args"]

    def test_works_disabled(self):
        observe.enable(False)
        try:
            meter = observe.StepMeter()
            meter.start()
            dt = meter.stop()
            assert dt >= 0 and meter.steps == 1
            assert not observe.tracer().events
        finally:
            observe.enable(None)

    def test_peak_tflops_table(self):
        assert observe.peak_tflops_for("TPU v5 lite") == 197.0
        assert observe.peak_tflops_for("TPU v4") == 275.0
        assert observe.peak_tflops_for("cpu") is None


@pytest.fixture()
def jax_cache(tmp_path, monkeypatch, telemetry):
    """Fresh persistent compile cache bound for the test, restored after:
    min-compile-time 0 so even toy programs persist entries (the
    hit/miss telemetry needs real cache traffic)."""
    import jax

    from torchdistx_tpu.jax_bridge import materialize as mat

    monkeypatch.setenv("TDX_CACHE_MIN_COMPILE_S", "0")
    monkeypatch.setattr(mat, "_cache_enabled", False)
    prev_dir = getattr(jax.config, "jax_compilation_cache_dir", None)
    cache = tmp_path / "xla_cache"
    cache.mkdir()
    yield str(cache)
    jax.config.update("jax_compilation_cache_dir", prev_dir)
    try:
        from jax._src import compilation_cache as cc

        cc.reset_cache()
    except Exception:
        pass
    mat._cache_enabled = False


class TestMaterializeTelemetry:
    """Tier-1 contract: a CPU materialize_module_jax run emits compile +
    materialize spans and compile-cache counters."""

    def _materialize_linear(self, cache):
        import torch

        from torchdistx_tpu.deferred_init import deferred_init
        from torchdistx_tpu.jax_bridge import materialize_module_jax

        with tdx_config.override(cache_dir=cache):
            m = deferred_init(torch.nn.Linear, 16, 8)
            return materialize_module_jax(m, seed=0)

    def test_spans_and_cache_counters(self, jax_cache):
        params = self._materialize_linear(jax_cache)
        assert set(params) == {"weight", "bias"}
        names = [e["name"] for e in observe.tracer().events if e["ph"] == "X"]
        for expected in ("record", "bridge.build_init_fn", "jax.lower",
                         "jax.compile", "jax.execute", "jax.materialize"):
            assert expected in names, f"missing span {expected!r} in {names}"
        snap = {r["name"]: r.get("value")
                for r in observe.counters().snapshot()}
        assert snap.get("tdx.jax.compile_cache_miss", 0) >= 1
        assert snap["tdx.graph.ops_recorded"] >= 2
        assert snap["tdx.graph.fakes_created"] >= 2
        assert snap["tdx.jax.bytes_materialized"] >= (16 * 8 + 8) * 4
        assert snap["tdx.jax.materialize_gbps"] > 0

    def test_second_run_hits_cache(self, jax_cache):
        self._materialize_linear(jax_cache)
        self._materialize_linear(jax_cache)
        snap = {r["name"]: r.get("value")
                for r in observe.counters().snapshot()}
        assert snap.get("tdx.jax.compile_cache_miss", 0) >= 1
        assert snap.get("tdx.jax.compile_cache_hit", 0) >= 1

    def test_trace_file_is_perfetto_loadable_shape(self, jax_cache, tmp_path):
        self._materialize_linear(jax_cache)
        written = observe.flush(trace_dir=str(tmp_path / "traces"))
        doc = json.load(open(written["trace"]))
        assert isinstance(doc["traceEvents"], list) and doc["traceEvents"]
        # every complete event carries the chrome-required keys
        for e in doc["traceEvents"]:
            if e.get("ph") == "X":
                assert {"name", "ts", "dur", "pid", "tid"} <= set(e)


class TestTrainStepTelemetry:
    def test_two_steps_emit_spans_and_gauges(self, telemetry):
        import numpy as np
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh

        from torchdistx_tpu.models import make_llama
        from torchdistx_tpu.models.configs import TransformerConfig

        from torchdistx_tpu.parallel.train import make_train_step

        cfg = TransformerConfig(
            vocab_size=64, d_model=32, n_layers=1, n_heads=2, d_ff=64,
            max_seq_len=16, dtype=jnp.float32,
        )
        model = make_llama(cfg)
        mesh = Mesh(np.asarray(jax.devices("cpu")[:1]), ("dp",))
        tokens = jax.random.randint(jax.random.PRNGKey(0), (2, 16), 0, 64)
        params = jax.jit(model.init)(jax.random.PRNGKey(1), tokens)
        init_state, train_step, shard_batch = make_train_step(model, cfg, mesh)
        state = init_state(params)
        batch = shard_batch(tokens)
        for _ in range(2):
            state, metrics = train_step(state, batch)
        steps = [e for e in observe.tracer().events
                 if e["ph"] == "X" and e["name"] == "train.step"]
        assert len(steps) == 2
        assert all(e["args"]["tokens_per_s"] > 0 for e in steps)
        snap = {r["name"]: r["value"] for r in observe.counters().snapshot()}
        assert snap["tdx.train.tokens_per_s"] > 0
        assert float(metrics["loss"]) > 0


class TestTraceCLI:
    def _make_trace_dir(self, tmp_path):
        with observe.span("jax.compile", category="jax"):
            time.sleep(0.002)
        observe.counter("tdx.jax.compile_cache_hit").inc(3)
        observe.counter("tdx.jax.compile_cache_miss").inc()
        observe.counter("tdx.bench.platform_fallback").inc()
        d = tmp_path / "traces"
        observe.flush(trace_dir=str(d))
        return d

    def test_summary(self, telemetry, tmp_path):
        d = self._make_trace_dir(tmp_path)
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "tdx_trace.py"),
             "summary", str(d)],
            capture_output=True, text=True, timeout=60,
        )
        assert out.returncode == 0, out.stderr
        assert "jax.compile" in out.stdout
        assert "3 hit / 1 miss" in out.stdout
        assert "75% hit ratio" in out.stdout
        assert "platform fallbacks: 1" in out.stdout

    def test_chrome_merge(self, telemetry, tmp_path):
        d = self._make_trace_dir(tmp_path)
        merged = tmp_path / "merged.json"
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "tdx_trace.py"),
             "chrome", str(d), "-o", str(merged)],
            capture_output=True, text=True, timeout=60,
        )
        assert out.returncode == 0, out.stderr
        doc = json.load(open(merged))
        assert any(e.get("name") == "jax.compile" for e in doc["traceEvents"])

    def test_empty_dir_exit_code(self, tmp_path):
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "tdx_trace.py"),
             "summary", str(tmp_path)],
            capture_output=True, text=True, timeout=60,
        )
        assert out.returncode == 2
