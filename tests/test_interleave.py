"""Interleaved (virtual-stage) 1F1B: schedule simulator + SPMD executor.

Layer 1 (pure numpy, fast): fuzz the static schedule tables over a
(pp, v, m) grid — dependency order, capacity, exactly-once coverage —
then REPLAY the tables through a symbolic dataflow machine that mirrors
the jnp executor tick for tick (same buffers, same slot reads), proving
every forward consumes exactly its predecessor's output and every
backward its successor's cotangent plus its own stashed input.

Layer 2 (jax): the executor's loss and gradients are differential-tested
against dense `jax.grad` and the flat 1F1B schedule.
"""

import numpy as np
import pytest

from torchdistx_tpu.parallel.interleave import (
    analytic_step_units_gpipe,
    flat_1f1b_segments,
    flat_1f1b_ticks,
    interleaved_schedule,
)

GRID = [
    (1, 1, 1), (1, 2, 3), (2, 1, 4), (2, 2, 4), (2, 2, 5),
    (3, 2, 5), (4, 2, 8), (4, 4, 16), (4, 3, 7), (8, 2, 16),
]

# The ISSUE's property-sweep grid: pp x v with an exact-fill and a
# ragged microbatch count per shape.  GRID above keeps its historical
# odd shapes (pp=1, pp=3); SWEEP is the documented coverage contract
# for the executor's clip-demotion (see interleaved_schedule's
# build-time guards).
SWEEP = [
    (pp, v, m) for pp in (2, 4, 8) for v in (1, 2, 4)
    for m in (pp, 2 * pp + 1)
]

_ALL = sorted(set(GRID + SWEEP))


@pytest.mark.parametrize("pp,v,m", _ALL)
class TestScheduleInvariants:
    def test_exactly_once_and_deps(self, pp, v, m):
        s = interleaved_schedule(pp, v, m)
        K = pp * v
        tF = -np.ones((K, m), np.int64)
        tB = -np.ones((K, m), np.int64)
        for d in range(pp):
            for t in range(s.T):
                if s.f_loc[d, t] >= 0:
                    k = s.f_loc[d, t] * pp + d
                    i = s.f_mb[d, t]
                    assert tF[k, i] < 0, "double forward"
                    tF[k, i] = t
                if s.b_loc[d, t] >= 0:
                    k = s.b_loc[d, t] * pp + d
                    i = s.b_mb[d, t]
                    assert tB[k, i] < 0, "double backward"
                    tB[k, i] = t
        assert (tF >= 0).all() and (tB >= 0).all(), "missing ops"
        for k in range(K):
            for i in range(m):
                if k > 0:
                    assert tF[k, i] > tF[k - 1, i], "fwd dep violated"
                if k < K - 1:
                    assert tB[k, i] > tB[k + 1, i], "bwd dep violated"
                    assert tB[k, i] > tF[k, i], "bwd before its fwd"
                else:
                    assert tB[k, i] == tF[k, i], "seed not same-tick"

    def test_symbolic_dataflow_replay(self, pp, v, m):
        # Mirror the jnp executor: per-device buf/dbuf (ring payloads),
        # inboxes, stash — tokens are ("F"|"B"|"X", chunk, mb).
        s = interleaved_schedule(pp, v, m)
        K = pp * v
        buf = [None] * pp
        dbuf = [None] * pp
        inbox_f = [dict() for _ in range(pp)]
        inbox_b = [dict() for _ in range(pp)]
        stash = [dict() for _ in range(pp)]
        for t in range(s.T):
            # arrivals (what was sent last tick)
            for d in range(pp):
                if s.f_arr[d, t] >= 0:
                    prev = (d - 1) % pp
                    assert buf[prev] is not None, "arrival with no send"
                    inbox_f[d][int(s.f_arr[d, t])] = buf[prev]
                if s.b_arr[d, t] >= 0:
                    nxt = (d + 1) % pp
                    assert dbuf[nxt] is not None
                    inbox_b[d][int(s.b_arr[d, t])] = dbuf[nxt]
            new_buf = [None] * pp
            new_dbuf = [None] * pp
            for d in range(pp):
                # ---- forward ----
                if s.f_loc[d, t] >= 0:
                    k = int(s.f_loc[d, t]) * pp + d
                    i = int(s.f_mb[d, t])
                    if s.f_rd[d, t] < 0:
                        assert k == 0, "batch feed off chunk 0"
                        inp = ("X", -1, i)
                    else:
                        inp = inbox_f[d][int(s.f_rd[d, t])]
                        assert inp == ("F", k - 1, i), (
                            f"F({k},{i}) read {inp}"
                        )
                    assert s.stash_w[d, t] >= 0
                    stash[d][int(s.stash_w[d, t])] = (k, i, inp)
                    new_buf[d] = ("F", k, i)
                # ---- backward ----
                if s.b_loc[d, t] >= 0:
                    k = int(s.b_loc[d, t]) * pp + d
                    i = int(s.b_mb[d, t])
                    if s.b_rd[d, t] < 0:
                        assert k == K - 1, "self-seed off the last chunk"
                    else:
                        cot = inbox_b[d][int(s.b_rd[d, t])]
                        assert cot == ("B", k + 1, i), (
                            f"B({k},{i}) read {cot}"
                        )
                    sk, si, _sinp = stash[d][int(s.stash_r[d, t])]
                    assert (sk, si) == (k, i), "stash mismatch"
                    new_dbuf[d] = ("B", k, i)
            buf, dbuf = new_buf, new_dbuf

    def test_slot_sizes_cover_tables(self, pp, v, m):
        s = interleaved_schedule(pp, v, m)
        for a, n in [
            (s.f_rd, s.n_f_slots), (s.f_arr, s.n_f_slots),
            (s.b_rd, s.n_b_slots), (s.b_arr, s.n_b_slots),
            (s.stash_w, s.n_stash_slots), (s.stash_r, s.n_stash_slots),
        ]:
            assert int(a.max()) < n


def _replay_pool(arr, rd, n_slots, T, what):
    """Replay one device's slot traffic: a slot allocated by an arrival
    at tick ``ta`` stays occupied until the tick AFTER its matching read
    — re-allocating it earlier would overwrite a value still in flight.
    """
    occupied = {}  # slot -> first tick it is free again
    peak = 0
    for t in range(T):
        for s in [s for s, rel in occupied.items() if rel <= t]:
            del occupied[s]
        s = int(arr[t])
        if s < 0:
            continue
        assert s not in occupied, (
            f"{what}: slot {s} re-allocated at tick {t} while a value "
            f"written earlier is still unread (freed at {occupied[s]})"
        )
        reads = np.flatnonzero(rd[t:] == s)
        assert reads.size, f"{what}: arrival at tick {t} is never read"
        occupied[s] = t + int(reads[0]) + 1
        peak = max(peak, len(occupied))
    assert not occupied or max(occupied.values()) <= T + 1
    assert peak <= n_slots, f"{what}: peak occupancy {peak} > {n_slots}"


@pytest.mark.parametrize("pp,v,m", SWEEP)
class TestSweepProperties:
    def test_slot_pool_never_double_allocates(self, pp, v, m):
        s = interleaved_schedule(pp, v, m)
        for d in range(pp):
            _replay_pool(s.f_arr[d], s.f_rd[d], s.n_f_slots, s.T,
                         f"f-inbox d{d}")
            _replay_pool(s.b_arr[d], s.b_rd[d], s.n_b_slots, s.T,
                         f"b-inbox d{d}")
            # stash: "arrival" is the forward's write, read by the
            # matching backward (the self-seed reads its own tick).
            _replay_pool(s.stash_w[d], s.stash_r[d], s.n_stash_slots,
                         s.T, f"stash d{d}")

    def test_active_indices_in_bounds_without_clip(self, pp, v, m):
        # Every index the executor reads for an ACTIVE op must already
        # be in-bounds — the jnp.clip at the read sites may only ever
        # rewrite the -1 of a masked-out op (trace-shape guard, not a
        # correctness device; see interleaved_schedule's build guards).
        s = interleaved_schedule(pp, v, m)
        fa, ba = s.f_loc >= 0, s.b_loc >= 0

        def ok(tab, n, mask):
            vals = tab[mask]
            return vals.size == 0 or (vals.min() >= 0 and vals.max() < n)

        assert ok(s.f_mb, m, fa) and ok(s.b_mb, m, ba)
        assert ok(s.f_loc, v, fa) and ok(s.b_loc, v, ba)
        assert ok(s.stash_w, s.n_stash_slots, fa)
        assert ok(s.stash_r, s.n_stash_slots, ba)
        assert ok(s.f_arr, s.n_f_slots, s.f_arr >= 0)
        assert ok(s.b_arr, s.n_b_slots, s.b_arr >= 0)
        # f_rd/b_rd are -1 for batch feeds / self-seeds only; every
        # other active read is a real inbox slot.
        assert ok(s.f_rd, s.n_f_slots, fa & (s.f_rd >= 0))
        assert ok(s.b_rd, s.n_b_slots, ba & (s.b_rd >= 0))
        # ... and those -1s appear exactly where the schedule says they
        # may: batch feeds on global chunk 0, self-seeds on the last.
        for d, t in zip(*np.nonzero(fa & (s.f_rd < 0))):
            assert s.f_loc[d, t] * pp + d == 0
        for d, t in zip(*np.nonzero(ba & (s.b_rd < 0))):
            assert s.b_loc[d, t] * pp + d == pp * v - 1

    def test_segments_cover_and_collapse(self, pp, v, m):
        # The phase-specialized executor's contract: segments tile
        # [0, T) contiguously and collapse to the classic warmup ->
        # steady -> cooldown shape with no idle runs.
        s = interleaved_schedule(pp, v, m)
        segs = s.segments()
        assert segs[0].t0 == 0 and segs[-1].t1 == s.T
        for a, b in zip(segs, segs[1:]):
            assert a.t1 == b.t0
        assert all(g.ticks > 0 for g in segs)
        assert [g.role for g in segs] == ["warmup", "steady", "cooldown"]
        assert segs[1].has_seed  # the last chunk self-seeds in steady
        # warmup runs only forwards, cooldown only backwards
        assert segs[0].has_f and not segs[0].has_b
        assert segs[2].has_b and not segs[2].has_f

    def test_analytic_units_beat_uniform(self, pp, v, m):
        # What the executor rebuild buys: skipping the vjp on warmup
        # ticks and the forward chain on cooldown ticks is a strict win
        # whenever a fill/drain phase exists (pp >= 2 always has one).
        s = interleaved_schedule(pp, v, m)
        assert s.analytic_step_units() < s.uniform_step_units()


def test_flat_segments_closed_form():
    for pp, m in [(2, 4), (4, 8), (8, 16)]:
        segs = flat_1f1b_segments(pp, m)
        assert sum(g.ticks for g in segs) == flat_1f1b_ticks(pp, m)
        assert [g.role for g in segs] == ["warmup", "steady", "cooldown"]


def test_headline_interleaved_beats_gpipe_analytically():
    # The bench's pp8_v4 headline in analytic units: deep interleave
    # (v=4, m=pp) closes the recompute-backward handicap (3 units vs
    # GPipe's stored 2) through sheer bubble elimination.
    for pp in (4, 8):
        s = interleaved_schedule(pp, 4, pp)
        assert s.analytic_step_units() < analytic_step_units_gpipe(pp, 4, pp)


def test_interleaving_beats_flat_bubble():
    # The point of the feature: chunk-sized fill/drain.  Compare tick
    # counts in equal work units (one flat tick == v chunk ticks).
    for pp, v, m in [(4, 2, 8), (4, 4, 16), (8, 2, 16), (8, 4, 32)]:
        s = interleaved_schedule(pp, v, m)
        flat_equiv = flat_1f1b_ticks(pp, m) * v
        assert s.T < flat_equiv, (pp, v, m, s.T, flat_equiv)
    # and the deeper the interleave, the lower the bubble fraction
    b2 = interleaved_schedule(8, 2, 16).bubble_fraction
    b4 = interleaved_schedule(8, 4, 32).bubble_fraction
    assert b4 < b2


# ---------------------------------------------------------------------------
# Executor differential tests
# ---------------------------------------------------------------------------

import jax
import jax.numpy as jnp

from torchdistx_tpu.models import TINY, TINY_MOE, make_llama, make_mixtral
from torchdistx_tpu.parallel import make_mesh
from torchdistx_tpu.parallel.pipeline import (
    pipeline_train_1f1b,
    pipeline_train_interleaved,
)
from torchdistx_tpu.parallel.train import lm_cross_entropy, make_train_step


class TestExecutor:
    @pytest.fixture(scope="class")
    def mesh(self):
        return make_mesh({"pp": 2, "dp": 4})

    def test_grads_match_dense(self, mesh):
        cfg = TINY.replace(n_layers=4)  # pp*v = 4 chunks of 1 layer
        m = make_llama(cfg)
        toks = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, cfg.vocab_size)
        params = m.init(jax.random.PRNGKey(0), toks)
        metrics, grads = jax.jit(
            lambda p, t: pipeline_train_interleaved(
                cfg, p, t, mesh, decomp=m.pipeline_decomposition(),
                n_microbatches=4, n_chunks=2,
            )
        )(params, toks)
        lref, gref = jax.value_and_grad(
            lambda p: lm_cross_entropy(m.apply(p, toks), toks)
        )(params)
        np.testing.assert_allclose(float(metrics["loss"]), float(lref), rtol=1e-6)
        diffs = jax.tree.map(
            lambda a, b: float(jnp.abs(a - b).max()), grads["params"], gref["params"]
        )
        assert max(jax.tree.leaves(diffs)) < 1e-5

    def test_matches_flat_1f1b(self, mesh):
        cfg = TINY.replace(n_layers=4)
        m = make_llama(cfg)
        toks = jax.random.randint(jax.random.PRNGKey(2), (8, 16), 0, cfg.vocab_size)
        params = m.init(jax.random.PRNGKey(0), toks)
        decomp = m.pipeline_decomposition()
        mi, gi = jax.jit(
            lambda p, t: pipeline_train_interleaved(
                cfg, p, t, mesh, decomp=decomp, n_microbatches=4, n_chunks=2,
            )
        )(params, toks)
        mf, gf = jax.jit(
            lambda p, t: pipeline_train_1f1b(
                cfg, p, t, mesh, decomp=decomp, n_microbatches=4,
            )
        )(params, toks)
        np.testing.assert_allclose(
            float(mi["loss"]), float(mf["loss"]), rtol=1e-6
        )
        diffs = jax.tree.map(
            lambda a, b: float(jnp.abs(a - b).max()), gi["params"], gf["params"]
        )
        assert max(jax.tree.leaves(diffs)) < 1e-5

    def test_moe_aux_rides_interleaved(self, mesh):
        # MoE: aux must equal the flat schedule's (same microbatched
        # mean semantics) and gradients must match it too.
        cfg = TINY_MOE.replace(n_layers=4)
        m = make_mixtral(cfg)
        toks = jax.random.randint(jax.random.PRNGKey(3), (8, 16), 0, cfg.vocab_size)
        params = m.init(jax.random.PRNGKey(0), toks)
        decomp = m.pipeline_decomposition()
        mi, gi = jax.jit(
            lambda p, t: pipeline_train_interleaved(
                cfg, p, t, mesh, decomp=decomp, n_microbatches=4, n_chunks=2,
            )
        )(params, toks)
        mf, gf = jax.jit(
            lambda p, t: pipeline_train_1f1b(
                cfg, p, t, mesh, decomp=decomp, n_microbatches=4,
            )
        )(params, toks)
        assert float(mi["aux"]) > 0.0
        np.testing.assert_allclose(float(mi["aux"]), float(mf["aux"]), rtol=1e-5)
        np.testing.assert_allclose(float(mi["loss"]), float(mf["loss"]), rtol=1e-6)
        diffs = jax.tree.map(
            lambda a, b: float(jnp.abs(a - b).max()), gi["params"], gf["params"]
        )
        assert max(jax.tree.leaves(diffs)) < 2e-5

    def test_via_make_train_step(self, mesh):
        cfg = TINY.replace(n_layers=4)
        m = make_llama(cfg)
        toks = jax.random.randint(jax.random.PRNGKey(4), (8, 16), 0, cfg.vocab_size)
        params = m.init(jax.random.PRNGKey(0), toks)
        init_state, step, shard_batch = make_train_step(
            m, cfg, mesh, pipeline=True, pipeline_schedule="interleaved",
            n_microbatches=4, n_chunks=2,
        )
        state = init_state(params)
        state, metrics = step(state, shard_batch(toks))
        assert np.isfinite(float(metrics["loss"]))
        assert float(metrics["grad_norm"]) > 0.0


class TestExecutorParity:
    """The segmented executor's acceptance gate: BITWISE-equal outputs
    to the uniform-tick executor.  Not allclose — the phase bodies must
    execute the identical op sequence per tick (masked where inactive),
    so any drift means a segment body diverged from the uniform one."""

    @pytest.fixture(scope="class")
    def mesh(self):
        return make_mesh({"pp": 2, "dp": 4})

    def _bitwise(self, a, b):
        leaves_a, treedef_a = jax.tree.flatten(a)
        leaves_b, treedef_b = jax.tree.flatten(b)
        assert treedef_a == treedef_b
        for la, lb in zip(leaves_a, leaves_b):
            assert np.array_equal(np.asarray(la), np.asarray(lb)), (
                "segmented executor output differs bitwise from uniform"
            )

    @pytest.mark.parametrize("moe", [False, True], ids=["llama", "mixtral"])
    def test_flat_segmented_matches_uniform(self, mesh, moe):
        cfg = (TINY_MOE if moe else TINY).replace(n_layers=4)
        m = make_mixtral(cfg) if moe else make_llama(cfg)
        toks = jax.random.randint(jax.random.PRNGKey(5), (8, 16), 0, cfg.vocab_size)
        params = m.init(jax.random.PRNGKey(0), toks)
        decomp = m.pipeline_decomposition()
        outs = {}
        for ex in ("segmented", "uniform"):
            outs[ex] = jax.jit(
                lambda p, t, ex=ex: pipeline_train_1f1b(
                    cfg, p, t, mesh, decomp=decomp, n_microbatches=4,
                    executor=ex,
                )
            )(params, toks)
        self._bitwise(outs["segmented"], outs["uniform"])

    @pytest.mark.parametrize("moe", [False, True], ids=["llama", "mixtral"])
    def test_interleaved_segmented_matches_uniform(self, mesh, moe):
        cfg = (TINY_MOE if moe else TINY).replace(n_layers=4)
        m = make_mixtral(cfg) if moe else make_llama(cfg)
        toks = jax.random.randint(jax.random.PRNGKey(6), (8, 16), 0, cfg.vocab_size)
        params = m.init(jax.random.PRNGKey(0), toks)
        decomp = m.pipeline_decomposition()
        outs = {}
        for ex in ("segmented", "uniform"):
            outs[ex] = jax.jit(
                lambda p, t, ex=ex: pipeline_train_interleaved(
                    cfg, p, t, mesh, decomp=decomp, n_microbatches=4,
                    n_chunks=2, executor=ex,
                )
            )(params, toks)
        self._bitwise(outs["segmented"], outs["uniform"])
