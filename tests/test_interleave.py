"""Interleaved (virtual-stage) 1F1B: schedule simulator + SPMD executor.

Layer 1 (pure numpy, fast): fuzz the static schedule tables over a
(pp, v, m) grid — dependency order, capacity, exactly-once coverage —
then REPLAY the tables through a symbolic dataflow machine that mirrors
the jnp executor tick for tick (same buffers, same slot reads), proving
every forward consumes exactly its predecessor's output and every
backward its successor's cotangent plus its own stashed input.

Layer 2 (jax): the executor's loss and gradients are differential-tested
against dense `jax.grad` and the flat 1F1B schedule.
"""

import numpy as np
import pytest

from torchdistx_tpu.parallel.interleave import (
    flat_1f1b_ticks,
    interleaved_schedule,
)

GRID = [
    (1, 1, 1), (1, 2, 3), (2, 1, 4), (2, 2, 4), (2, 2, 5),
    (3, 2, 5), (4, 2, 8), (4, 4, 16), (4, 3, 7), (8, 2, 16),
]


@pytest.mark.parametrize("pp,v,m", GRID)
class TestScheduleInvariants:
    def test_exactly_once_and_deps(self, pp, v, m):
        s = interleaved_schedule(pp, v, m)
        K = pp * v
        tF = -np.ones((K, m), np.int64)
        tB = -np.ones((K, m), np.int64)
        for d in range(pp):
            for t in range(s.T):
                if s.f_loc[d, t] >= 0:
                    k = s.f_loc[d, t] * pp + d
                    i = s.f_mb[d, t]
                    assert tF[k, i] < 0, "double forward"
                    tF[k, i] = t
                if s.b_loc[d, t] >= 0:
                    k = s.b_loc[d, t] * pp + d
                    i = s.b_mb[d, t]
                    assert tB[k, i] < 0, "double backward"
                    tB[k, i] = t
        assert (tF >= 0).all() and (tB >= 0).all(), "missing ops"
        for k in range(K):
            for i in range(m):
                if k > 0:
                    assert tF[k, i] > tF[k - 1, i], "fwd dep violated"
                if k < K - 1:
                    assert tB[k, i] > tB[k + 1, i], "bwd dep violated"
                    assert tB[k, i] > tF[k, i], "bwd before its fwd"
                else:
                    assert tB[k, i] == tF[k, i], "seed not same-tick"

    def test_symbolic_dataflow_replay(self, pp, v, m):
        # Mirror the jnp executor: per-device buf/dbuf (ring payloads),
        # inboxes, stash — tokens are ("F"|"B"|"X", chunk, mb).
        s = interleaved_schedule(pp, v, m)
        K = pp * v
        buf = [None] * pp
        dbuf = [None] * pp
        inbox_f = [dict() for _ in range(pp)]
        inbox_b = [dict() for _ in range(pp)]
        stash = [dict() for _ in range(pp)]
        for t in range(s.T):
            # arrivals (what was sent last tick)
            for d in range(pp):
                if s.f_arr[d, t] >= 0:
                    prev = (d - 1) % pp
                    assert buf[prev] is not None, "arrival with no send"
                    inbox_f[d][int(s.f_arr[d, t])] = buf[prev]
                if s.b_arr[d, t] >= 0:
                    nxt = (d + 1) % pp
                    assert dbuf[nxt] is not None
                    inbox_b[d][int(s.b_arr[d, t])] = dbuf[nxt]
            new_buf = [None] * pp
            new_dbuf = [None] * pp
            for d in range(pp):
                # ---- forward ----
                if s.f_loc[d, t] >= 0:
                    k = int(s.f_loc[d, t]) * pp + d
                    i = int(s.f_mb[d, t])
                    if s.f_rd[d, t] < 0:
                        assert k == 0, "batch feed off chunk 0"
                        inp = ("X", -1, i)
                    else:
                        inp = inbox_f[d][int(s.f_rd[d, t])]
                        assert inp == ("F", k - 1, i), (
                            f"F({k},{i}) read {inp}"
                        )
                    assert s.stash_w[d, t] >= 0
                    stash[d][int(s.stash_w[d, t])] = (k, i, inp)
                    new_buf[d] = ("F", k, i)
                # ---- backward ----
                if s.b_loc[d, t] >= 0:
                    k = int(s.b_loc[d, t]) * pp + d
                    i = int(s.b_mb[d, t])
                    if s.b_rd[d, t] < 0:
                        assert k == K - 1, "self-seed off the last chunk"
                    else:
                        cot = inbox_b[d][int(s.b_rd[d, t])]
                        assert cot == ("B", k + 1, i), (
                            f"B({k},{i}) read {cot}"
                        )
                    sk, si, _sinp = stash[d][int(s.stash_r[d, t])]
                    assert (sk, si) == (k, i), "stash mismatch"
                    new_dbuf[d] = ("B", k, i)
            buf, dbuf = new_buf, new_dbuf

    def test_slot_sizes_cover_tables(self, pp, v, m):
        s = interleaved_schedule(pp, v, m)
        for a, n in [
            (s.f_rd, s.n_f_slots), (s.f_arr, s.n_f_slots),
            (s.b_rd, s.n_b_slots), (s.b_arr, s.n_b_slots),
            (s.stash_w, s.n_stash_slots), (s.stash_r, s.n_stash_slots),
        ]:
            assert int(a.max()) < n


def test_interleaving_beats_flat_bubble():
    # The point of the feature: chunk-sized fill/drain.  Compare tick
    # counts in equal work units (one flat tick == v chunk ticks).
    for pp, v, m in [(4, 2, 8), (4, 4, 16), (8, 2, 16), (8, 4, 32)]:
        s = interleaved_schedule(pp, v, m)
        flat_equiv = flat_1f1b_ticks(pp, m) * v
        assert s.T < flat_equiv, (pp, v, m, s.T, flat_equiv)
    # and the deeper the interleave, the lower the bubble fraction
    b2 = interleaved_schedule(8, 2, 16).bubble_fraction
    b4 = interleaved_schedule(8, 4, 32).bubble_fraction
    assert b4 < b2


# ---------------------------------------------------------------------------
# Executor differential tests
# ---------------------------------------------------------------------------

import jax
import jax.numpy as jnp

from torchdistx_tpu.models import TINY, TINY_MOE, make_llama, make_mixtral
from torchdistx_tpu.parallel import make_mesh
from torchdistx_tpu.parallel.pipeline import (
    pipeline_train_1f1b,
    pipeline_train_interleaved,
)
from torchdistx_tpu.parallel.train import lm_cross_entropy, make_train_step


class TestExecutor:
    @pytest.fixture(scope="class")
    def mesh(self):
        return make_mesh({"pp": 2, "dp": 4})

    def test_grads_match_dense(self, mesh):
        cfg = TINY.replace(n_layers=4)  # pp*v = 4 chunks of 1 layer
        m = make_llama(cfg)
        toks = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, cfg.vocab_size)
        params = m.init(jax.random.PRNGKey(0), toks)
        metrics, grads = jax.jit(
            lambda p, t: pipeline_train_interleaved(
                cfg, p, t, mesh, decomp=m.pipeline_decomposition(),
                n_microbatches=4, n_chunks=2,
            )
        )(params, toks)
        lref, gref = jax.value_and_grad(
            lambda p: lm_cross_entropy(m.apply(p, toks), toks)
        )(params)
        np.testing.assert_allclose(float(metrics["loss"]), float(lref), rtol=1e-6)
        diffs = jax.tree.map(
            lambda a, b: float(jnp.abs(a - b).max()), grads["params"], gref["params"]
        )
        assert max(jax.tree.leaves(diffs)) < 1e-5

    def test_matches_flat_1f1b(self, mesh):
        cfg = TINY.replace(n_layers=4)
        m = make_llama(cfg)
        toks = jax.random.randint(jax.random.PRNGKey(2), (8, 16), 0, cfg.vocab_size)
        params = m.init(jax.random.PRNGKey(0), toks)
        decomp = m.pipeline_decomposition()
        mi, gi = jax.jit(
            lambda p, t: pipeline_train_interleaved(
                cfg, p, t, mesh, decomp=decomp, n_microbatches=4, n_chunks=2,
            )
        )(params, toks)
        mf, gf = jax.jit(
            lambda p, t: pipeline_train_1f1b(
                cfg, p, t, mesh, decomp=decomp, n_microbatches=4,
            )
        )(params, toks)
        np.testing.assert_allclose(
            float(mi["loss"]), float(mf["loss"]), rtol=1e-6
        )
        diffs = jax.tree.map(
            lambda a, b: float(jnp.abs(a - b).max()), gi["params"], gf["params"]
        )
        assert max(jax.tree.leaves(diffs)) < 1e-5

    def test_moe_aux_rides_interleaved(self, mesh):
        # MoE: aux must equal the flat schedule's (same microbatched
        # mean semantics) and gradients must match it too.
        cfg = TINY_MOE.replace(n_layers=4)
        m = make_mixtral(cfg)
        toks = jax.random.randint(jax.random.PRNGKey(3), (8, 16), 0, cfg.vocab_size)
        params = m.init(jax.random.PRNGKey(0), toks)
        decomp = m.pipeline_decomposition()
        mi, gi = jax.jit(
            lambda p, t: pipeline_train_interleaved(
                cfg, p, t, mesh, decomp=decomp, n_microbatches=4, n_chunks=2,
            )
        )(params, toks)
        mf, gf = jax.jit(
            lambda p, t: pipeline_train_1f1b(
                cfg, p, t, mesh, decomp=decomp, n_microbatches=4,
            )
        )(params, toks)
        assert float(mi["aux"]) > 0.0
        np.testing.assert_allclose(float(mi["aux"]), float(mf["aux"]), rtol=1e-5)
        np.testing.assert_allclose(float(mi["loss"]), float(mf["loss"]), rtol=1e-6)
        diffs = jax.tree.map(
            lambda a, b: float(jnp.abs(a - b).max()), gi["params"], gf["params"]
        )
        assert max(jax.tree.leaves(diffs)) < 2e-5

    def test_via_make_train_step(self, mesh):
        cfg = TINY.replace(n_layers=4)
        m = make_llama(cfg)
        toks = jax.random.randint(jax.random.PRNGKey(4), (8, 16), 0, cfg.vocab_size)
        params = m.init(jax.random.PRNGKey(0), toks)
        init_state, step, shard_batch = make_train_step(
            m, cfg, mesh, pipeline=True, pipeline_schedule="interleaved",
            n_microbatches=4, n_chunks=2,
        )
        state = init_state(params)
        state, metrics = step(state, shard_batch(toks))
        assert np.isfinite(float(metrics["loss"]))
        assert float(metrics["grad_norm"]) > 0.0
