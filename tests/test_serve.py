"""Serving-runtime tests (ISSUE 7 tentpole): the continuous-batching
engine's outputs equal the unbatched no-cache oracle through batching,
staggered admission, page-pool preemption, and injected replica faults;
replica bring-up through a warmed registry performs zero local compiles;
the serve telemetry vocabulary is emitted."""

import os
import shutil
import tempfile

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import torchdistx_tpu.config as tdx_config
from torchdistx_tpu import chaos, observe
from torchdistx_tpu.jax_bridge import materialize as mat
from torchdistx_tpu.models import TransformerConfig
from torchdistx_tpu.serve import (
    Request,
    ServeConfig,
    ServeEngine,
    oracle_generate,
    serve_program_specs,
    spin_up_replica,
    warm_serving,
)
from torchdistx_tpu.serve.programs import compile_serving_program

# Small enough that a full engine compiles in a few seconds on the
# 1-core CI box; vocab big enough that greedy argmax ties are
# vanishingly unlikely.
LLAMA = TransformerConfig(
    vocab_size=128, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
    d_ff=64, max_seq_len=64, dtype=jnp.float32,
)
GPT2 = TransformerConfig(
    vocab_size=128, d_model=32, n_layers=2, n_heads=4, d_ff=64,
    max_seq_len=64, use_bias=True, activation="gelu", norm="layernorm",
    positions="learned", tie_embeddings=True, dtype=jnp.float32,
)
SCFG = ServeConfig(max_batch=2, page_size=8, n_pages=16,
                   max_pages_per_seq=3, prefill_buckets=(8, 16))


def _params(family, cfg, seed=0):
    specs = serve_program_specs(family, cfg, SCFG, seed=seed)
    init = specs[0]
    compiled, _ = compile_serving_program(init)
    return jax.tree.unflatten(init.treedef, list(compiled()))


@pytest.fixture(scope="module")
def llama_params():
    return _params("llama", LLAMA)


@pytest.fixture(scope="module")
def llama_engine(llama_params):
    eng = ServeEngine("llama", LLAMA, llama_params, serve_cfg=SCFG)
    eng.warmup()
    return eng


def _check_oracle(eng, reqs, out):
    for r in reqs:
        want, want_logits = oracle_generate(
            eng.family, eng.cfg, eng.params, r.tokens, r.max_new_tokens,
            r.eos_id,
        )
        assert out[r.rid] == want, (r.rid, out[r.rid], want)
        np.testing.assert_allclose(
            eng.final_logits[r.rid], want_logits, atol=1e-4,
            err_msg=f"final logits of {r.rid}",
        )


def test_batched_engine_matches_unbatched_oracle(llama_engine):
    reqs = [
        Request("a", [5, 9, 2], max_new_tokens=6),
        Request("b", [17, 3, 3, 8, 1, 101], max_new_tokens=5),
        Request("c", [7] * 11, max_new_tokens=4),
    ]
    out = llama_engine.run(reqs)
    assert {"a", "b", "c"} <= set(out)
    _check_oracle(llama_engine, reqs, out)


def test_continuous_batching_staggered_arrivals(llama_engine):
    """More requests than lanes, arriving over time: every one completes
    and matches its oracle — admission interleaves with decode instead
    of waiting for the batch to drain."""
    reqs = [
        Request(f"s{i}", [(3 * i + j) % 128 for j in range(2 + i)],
                max_new_tokens=3 + (i % 3), arrival_step=i)
        for i in range(5)
    ]
    out = llama_engine.run(reqs)
    assert {r.rid for r in reqs} <= set(out)
    _check_oracle(llama_engine, reqs, out)


def test_eos_retires_early(llama_engine):
    r = Request("e", [5, 9, 2], max_new_tokens=6)
    first = oracle_generate(
        llama_engine.family, LLAMA, llama_engine.params, r.tokens, 1
    )[0][0]
    r2 = Request("e", [5, 9, 2], max_new_tokens=6, eos_id=first)
    out = llama_engine.run([r2])
    assert out["e"] == [first]  # retired at the first token, during prefill


def test_page_pool_exhaustion_preempts_and_recovers(llama_params):
    """A pool too small for two long generations forces preemption: the
    youngest lane is requeued (counted), and every output still equals
    the oracle."""
    scfg = ServeConfig(max_batch=2, page_size=4, n_pages=7,
                       max_pages_per_seq=6, prefill_buckets=(8,))
    eng = ServeEngine("llama", LLAMA, llama_params, serve_cfg=scfg)
    observe.enable(True)
    try:
        def _ttft_count():
            for r in observe.counters().snapshot():
                if r["name"] == "tdx.serve.ttft_s":
                    return r["count"]
            return 0

        before = observe.counter("tdx.serve.preempted_requests").value
        ttft_before = _ttft_count()
        reqs = [
            Request("p0", [1, 2, 3, 4, 5, 6], max_new_tokens=8),
            Request("p1", [9, 8, 7, 6, 5, 4], max_new_tokens=8),
        ]
        out = eng.run(reqs)
        assert observe.counter("tdx.serve.preempted_requests").value > before
        # Re-prefills of preempted requests must not contribute bogus
        # TTFT samples: exactly one sample per request.
        assert _ttft_count() == ttft_before + len(reqs)
        _check_oracle(eng, reqs, out)
    finally:
        observe.enable(None)


def test_chaos_serve_fault_requeues_and_converges(llama_params,
                                                  llama_engine):
    """serve@N=raise mid-batch: active lanes are requeued and
    regenerated; outputs equal the fault-free oracle (recompute
    preemption, docs/serving.md)."""
    streamed: dict = {}
    eng = ServeEngine(
        "llama", LLAMA, llama_params, serve_cfg=SCFG,
        on_token=lambda rid, tok: streamed.setdefault(rid, []).append(tok),
    )
    # Same serve shape as the module fixture: reuse its compiled
    # programs (compiled executables are pure; this test targets the
    # engine loop, not compilation).
    eng._programs.update(llama_engine._programs)
    observe.enable(True)
    chaos.install("serve@2=raise;serve@4=slow:0.01")
    try:
        before = observe.counter("tdx.serve.preempted_requests").value
        reqs = [
            Request("x", [1, 2, 3], max_new_tokens=5),
            Request("y", [9, 8, 7, 6], max_new_tokens=4),
        ]
        out = eng.run(reqs)
        assert observe.counter("tdx.serve.preempted_requests").value > before
        injected = chaos.active_plan()
        assert not injected.pending(), "both faults should have fired"
        _check_oracle(eng, reqs, out)
        # The replayed prefix of a requeued request must not stream
        # twice: on_token sees each position exactly once.
        assert streamed == out, (streamed, out)
    finally:
        chaos.clear()
        observe.enable(None)


def test_fault_during_prefill_requeues_without_leaking_pages(llama_params):
    """A retryable fault while the prefill program compiles/executes —
    after the request left the queue but before its lane is active —
    must requeue the request and free its pages, not drop it (the
    chaos `compile` site fires inside the engine's first lazy program
    compile, which happens during prefill)."""
    eng = ServeEngine("llama", LLAMA, llama_params, serve_cfg=SCFG)
    observe.enable(True)
    chaos.install("compile@1=raise")
    try:
        before = observe.counter("tdx.serve.preempted_requests").value
        r = Request("pf", [8, 6, 4], max_new_tokens=3)
        out = eng.run([r])
        assert observe.counter("tdx.serve.preempted_requests").value > before
        assert eng.kv.pages_in_use == 0
        _check_oracle(eng, [r], out)
    finally:
        chaos.clear()
        observe.enable(None)


@pytest.mark.slow  # ~7 s of gpt2-family compiles; `make chaos-test` runs it
def test_gpt2_decode_matches_oracle():
    params = _params("gpt2", GPT2)
    eng = ServeEngine("gpt2", GPT2, params, serve_cfg=SCFG)
    reqs = [Request("g", [4, 5, 6, 7], max_new_tokens=4),
            Request("h", [40, 40, 2], max_new_tokens=3)]
    out = eng.run(reqs)
    _check_oracle(eng, reqs, out)


def test_run_budget_is_per_call_not_lifetime(llama_engine):
    """A long-lived replica (large cumulative step count) must still
    serve new run() calls — max_steps budgets THIS call."""
    llama_engine._step_no = 10**6
    r = Request("life", [2, 4, 6], max_new_tokens=2)
    out = llama_engine.run([r], max_steps=100)
    assert out["life"] == oracle_generate(
        "llama", LLAMA, llama_engine.params, r.tokens, 2)[0]


def test_submit_validation(llama_engine):
    with pytest.raises(ValueError, match="empty prompt"):
        llama_engine.submit(Request("bad", [], max_new_tokens=1))
    with pytest.raises(ValueError, match="max_context"):
        llama_engine.submit(Request("big", [1] * 20, max_new_tokens=20))
    # A zero budget would emit prefill's token while the oracle
    # generates nothing: rejected.
    with pytest.raises(ValueError, match="max_new_tokens"):
        llama_engine.submit(Request("zero", [1, 2], max_new_tokens=0))


def test_prompt_beyond_largest_bucket_serves_chunked(llama_engine):
    """A prompt larger than the largest prefill bucket used to be
    rejected at submit; chunked prefill serves it (and it still matches
    the oracle bitwise)."""
    assert 18 > llama_engine.scfg.prefill_buckets[-1]
    r = Request("wide", [(7 * i) % 128 for i in range(18)],
                max_new_tokens=2)
    out = llama_engine.run([r])
    _check_oracle(llama_engine, [r], out)


def test_serve_telemetry_vocabulary(llama_params, llama_engine):
    """The documented tdx.serve.* counter/gauge/histogram names are all
    emitted by one served batch (docs/observability.md)."""
    eng = ServeEngine("llama", LLAMA, llama_params, serve_cfg=SCFG)
    eng._programs.update(llama_engine._programs)
    observe.enable(True)
    try:
        eng.run([Request("t", [3, 1, 4], max_new_tokens=3)])
        snap = {r["name"]: r for r in observe.counters().snapshot()}
        for name in (
            "tdx.serve.prefills",
            "tdx.serve.decode_steps",
            "tdx.serve.requests_completed",
            "tdx.serve.kv_pages_in_use",
            "tdx.serve.queue_depth",
            "tdx.serve.tokens_per_s",
            "tdx.serve.ttft_s",
        ):
            assert name in snap, sorted(snap)
        assert snap["tdx.serve.requests_completed"]["value"] >= 1
        assert snap["tdx.serve.ttft_s"]["count"] >= 1
        # retirement freed the pages
        assert eng.kv.pages_in_use == 0
    finally:
        observe.enable(None)


@pytest.mark.slow  # ~15 s of compiles; `make chaos-test` + serve-smoke run it
def test_registry_warmed_bring_up_zero_local_compiles():
    """The autoscaling contract: warm_serving publishes the whole
    program set; a replica with a FRESH local cache then brings up with
    ZERO local compiles (every program a registry-fed hit) and still
    matches the oracle."""
    reg = tempfile.mkdtemp(prefix="tdx_serve_reg_")
    warm_cache = tempfile.mkdtemp(prefix="tdx_serve_ca_")
    fresh_cache = tempfile.mkdtemp(prefix="tdx_serve_cb_")
    observe.enable(True)
    # Persist even trivial programs: the `cow` page-copy compiles in
    # ~0.1 s on a warm process, straddling jax's default
    # min_compile_time_secs — whether warm_serving's cache file (and so
    # the registry entry) exists would otherwise depend on process
    # warmth, not the contract under test.
    old_min = os.environ.get("TDX_CACHE_MIN_COMPILE_S")
    os.environ["TDX_CACHE_MIN_COMPILE_S"] = "0"
    mat._reset_cache_binding()
    try:
        summary = warm_serving("llama", LLAMA, warm_cache,
                               registry_dir=reg, serve_cfg=SCFG)
        assert not summary["unwarmed"], summary
        assert summary["programs"] == len(summary["program_reports"])
        names = {r["program"] for r in summary["program_reports"]}
        assert names == {"init", "prefill-8", "prefill-16",
                         "chunk-8", "chunk-16", "cow", "decode",
                         "verify-2", "verify-4"}

        mat._reset_cache_binding()
        base = {r["name"]: r["value"]
                for r in observe.counters().snapshot()
                if r["type"] == "counter"}
        with tdx_config.override(cache_dir=fresh_cache, registry_dir=reg):
            eng = spin_up_replica(LLAMA, family="llama", serve_cfg=SCFG)
        snap = {r["name"]: r["value"]
                for r in observe.counters().snapshot()
                if r["type"] == "counter"}
        miss = (snap.get("tdx.jax.compile_cache_miss", 0)
                - base.get("tdx.jax.compile_cache_miss", 0))
        hits = (snap.get("tdx.jax.compile_cache_hit", 0)
                - base.get("tdx.jax.compile_cache_hit", 0))
        assert miss == 0, eng.bring_up_outcomes
        assert hits >= 4, eng.bring_up_outcomes
        assert set(eng.bring_up_outcomes.values()) == {"hit"}

        r = Request("w", [11, 22, 33], max_new_tokens=4)
        out = eng.run([r])
        _check_oracle(eng, [r], out)
    finally:
        observe.enable(None)
        if old_min is None:
            os.environ.pop("TDX_CACHE_MIN_COMPILE_S", None)
        else:
            os.environ["TDX_CACHE_MIN_COMPILE_S"] = old_min
        mat._reset_cache_binding()
        for d in (reg, warm_cache, fresh_cache):
            shutil.rmtree(d, ignore_errors=True)


def test_program_fingerprints_are_shape_sensitive():
    """Registry identity: same shape → same fingerprint; any serve-shape
    change → different fingerprint (a mismatched fetch is impossible by
    construction)."""
    a = {s.name: s.program_fp
         for s in serve_program_specs("llama", LLAMA, SCFG)}
    b = {s.name: s.program_fp
         for s in serve_program_specs("llama", LLAMA, SCFG)}
    assert a == b
    c = {s.name: s.program_fp
         for s in serve_program_specs(
             "llama", LLAMA,
             ServeConfig(max_batch=4, page_size=8, n_pages=16,
                         max_pages_per_seq=3, prefill_buckets=(8, 16)))}
    assert c["decode"] != a["decode"]
    # ...but the init program does not depend on the serve shape: its
    # (most expensive) artifact survives a pure capacity change.
    assert c["init"] == a["init"]
    d = {s.name: s.program_fp
         for s in serve_program_specs("llama", LLAMA, SCFG, seed=1)}
    assert d["init"] != a["init"]
    # max_new_tokens / prefill_chunk / prefix_cache / spec_decode /
    # spec_k are host-side knobs no compiled program reads: changing
    # them must NOT invalidate a warmed registry.
    e = {s.name: s.program_fp
         for s in serve_program_specs(
             "llama", LLAMA,
             ServeConfig(max_batch=2, page_size=8, n_pages=16,
                         max_pages_per_seq=3, prefill_buckets=(8, 16),
                         max_new_tokens=99, prefill_chunk=5,
                         prefix_cache=False, spec_decode=False,
                         spec_k=2))}
    assert e == a
    # ...while spec_buckets IS a shape knob: it picks which verify-<k>
    # programs exist (each one's own fp depends only on its k).
    assert {"verify-2", "verify-4"} <= set(a)
    f = {s.name: s.program_fp
         for s in serve_program_specs(
             "llama", LLAMA,
             ServeConfig(max_batch=2, page_size=8, n_pages=16,
                         max_pages_per_seq=3, prefill_buckets=(8, 16),
                         spec_buckets=(3,)))}
    assert "verify-3" in f and "verify-4" not in f
    assert f["decode"] == a["decode"]


# ---------------------------------------------------------------------------
# speculative decoding (ISSUE 19): drafts accepted, bitwise-oracle kept
# ---------------------------------------------------------------------------


def test_spec_decode_accepts_drafts_and_matches_oracle(llama_engine):
    """Self-drafting: after one generation taught the drafter a greedy
    chain, a repeat of the same prompt must accept draft tokens (more
    than one token per verify tick) while staying bitwise-equal to the
    unbatched oracle."""
    eng = llama_engine
    assert eng.scfg.spec_decode and eng._drafter is not None
    r1 = Request("sp-a", [23, 42, 17], max_new_tokens=6)
    out1 = eng.run([r1])
    _check_oracle(eng, [r1], out1)
    ticks0 = eng.spec_verify_ticks
    drafted0, accepted0 = eng.spec_drafted, eng.spec_accepted
    r2 = Request("sp-b", [23, 42, 17], max_new_tokens=6)
    out2 = eng.run([r2])
    _check_oracle(eng, [r2], out2)
    assert out2["sp-b"] == out1["sp-a"]
    assert eng.spec_verify_ticks > ticks0
    assert eng.spec_drafted > drafted0
    # The repeat's whole chain was in the drafter: accepts happened, so
    # the run took fewer verify ticks than it emitted tokens.
    accepted = eng.spec_accepted - accepted0
    assert accepted > 0, (eng.spec_drafted - drafted0, accepted)
    assert eng.spec_verify_ticks - ticks0 < 6


def test_spec_kill_switch_serves_plain_decode(llama_params, llama_engine):
    """``spec_decode=False`` (the TDX_SPEC_DECODE=0 path): no drafter,
    no verify ticks, identical tokens — the switch trades throughput,
    never output."""
    scfg = ServeConfig(max_batch=2, page_size=8, n_pages=16,
                       max_pages_per_seq=3, prefill_buckets=(8, 16),
                       spec_decode=False)
    eng = ServeEngine("llama", LLAMA, llama_params, serve_cfg=scfg)
    eng._programs.update(llama_engine._programs)
    assert eng._drafter is None and not eng.scfg.spec_decode
    reqs = [Request("ks-a", [23, 42, 17], max_new_tokens=5),
            Request("ks-b", [7] * 9, max_new_tokens=4)]
    out = eng.run(reqs)
    _check_oracle(eng, reqs, out)
    assert eng.spec_verify_ticks == 0 and eng.spec_drafted == 0
    # the env-var spelling resolves the same way
    with tdx_config.override(spec_decode=False):
        eng2 = ServeEngine("llama", LLAMA, llama_params, serve_cfg=SCFG)
    assert eng2._drafter is None and not eng2.scfg.spec_decode


def test_spec_decode_through_preemption_matches_oracle(llama_params):
    """Page-pool preemption while lanes are speculating: draft shedding
    plus token-level KV rollback keep every output bitwise-equal to the
    oracle and the preempted lane's requeue intact."""
    scfg = ServeConfig(max_batch=2, page_size=4, n_pages=7,
                       max_pages_per_seq=6, prefill_buckets=(8,))
    eng = ServeEngine("llama", LLAMA, llama_params, serve_cfg=scfg)
    observe.enable(True)
    try:
        before = observe.counter("tdx.serve.preempted_requests").value
        # Repetitive prompts make the n-gram drafter propose from the
        # first decode tick, so speculation is live when the pool runs dry.
        reqs = [
            Request("pp0", [7] * 6, max_new_tokens=8),
            Request("pp1", [7, 7, 7, 9, 9, 9], max_new_tokens=8),
        ]
        out = eng.run(reqs)
        assert observe.counter("tdx.serve.preempted_requests").value > before
        assert eng.spec_drafted > 0
        _check_oracle(eng, reqs, out)
    finally:
        observe.enable(None)
    eng.drain()
    assert eng.kv.pages_in_use == 0


def test_chaos_raise_verify_requeues_and_converges(llama_params,
                                                   llama_engine):
    """serve@N=raise:verify fires at the next speculative verify tick —
    after drafting and KV growth, before accept/rollback: active lanes
    requeue and regenerate, outputs equal the fault-free oracle, and no
    pages leak."""
    eng = ServeEngine("llama", LLAMA, llama_params, serve_cfg=SCFG)
    eng._programs.update(llama_engine._programs)
    observe.enable(True)
    # Teach the drafter this chain so the targeted tick really drafts.
    warm = Request("vf-w", [7] * 8, max_new_tokens=4)
    eng.run([warm])
    chaos.install(f"serve@{eng._step_no + 3}=raise:verify")
    try:
        before = observe.counter("tdx.serve.preempted_requests").value
        reqs = [Request("vf-a", [7] * 8, max_new_tokens=6),
                Request("vf-b", [9, 8, 7, 6], max_new_tokens=4)]
        out = eng.run(reqs)
        assert not chaos.active_plan().pending(), "the fault never fired"
        assert observe.counter("tdx.serve.preempted_requests").value > before
        _check_oracle(eng, reqs, out)
    finally:
        chaos.clear()
        observe.enable(None)
    eng.drain()
    assert eng.kv.pages_in_use == 0
    assert not eng.kv._ref
