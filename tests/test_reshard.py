"""Elastic resharding: topology-migrating checkpoint redistribution.

Covers the :mod:`torchdistx_tpu.reshard` contract (docs/robustness.md
§Resharding):

* offline ``reshard_checkpoint`` is bitwise-exact leaf-by-leaf — params
  AND optimizer state, bfloat16 included — for shrink, grow, and
  axis-reshape plan pairs;
* the manifest topology block round-trips and old manifests without it
  still verify;
* ``run_elastic`` resume onto a different mesh reshards in-flight
  (``needs_reshard`` routing) and continues the exact trajectory;
* host memory during a transfer stays bounded by the chunk budget even
  when a single leaf exceeds it;
* injected ``reshard``-site chaos (raise / slow / corrupt) degrades and
  never corrupts: source untouched, no committed destination, typed
  :class:`ReshardError`;
* the ``auto`` pipeline-executor spelling resolves per schedule size.

The mesh pairs are carved out of the 8-device virtual CPU pool
(conftest.py), so a "host count change" is a device-subset change —
same trick the FSDP tests use.
"""

import json
import os
import signal
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from torchdistx_tpu import chaos, observe, reshard
from torchdistx_tpu.parallel.mesh import make_mesh
from torchdistx_tpu.parallel.sharding import (
    ShardingPlan, fsdp_plan, gspmd_2d_plan, plan_digest, spec_str,
)
from torchdistx_tpu.reshard import ReshardError
from torchdistx_tpu.utils.checkpoint import (
    leaf_storage_name,
    read_manifest,
    restore_checkpoint,
    save_checkpoint,
    state_topology,
    verify_checkpoint,
)
from torchdistx_tpu.utils.failures import run_elastic

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _mesh(axes):
    n = 1
    for s in axes.values():
        n *= s
    return make_mesh(dict(axes), devices=jax.devices()[:n])


def _state():
    """Params + real adamw optimizer state + bf16 leaf + scalar step."""
    params = {
        "dense": {
            "kernel": jnp.arange(96, dtype=jnp.float32).reshape(8, 12),
            "bias": jnp.linspace(0.0, 1.0, 12).astype(jnp.bfloat16),
        },
        "embed": jnp.arange(64, dtype=jnp.float32).reshape(16, 4) * 0.25,
    }
    return {
        "params": params,
        "opt": optax.adamw(3e-4).init(params),
        "step": jnp.int32(7),
    }


def _shard(tree, plan, mesh):
    flat, td = jax.tree_util.tree_flatten_with_path(tree)
    return jax.tree_util.tree_unflatten(td, [
        jax.device_put(
            leaf, plan.sharding_for(leaf_storage_name(kp), leaf.shape, mesh))
        for kp, leaf in flat
    ])


def _bits(x):
    return np.asarray(x).reshape(-1).view(np.uint8).tobytes()


def _assert_bitwise(got, want):
    assert jax.tree_util.tree_structure(got) == jax.tree_util.tree_structure(want)
    for g, w in zip(jax.tree_util.tree_leaves(got), jax.tree_util.tree_leaves(want)):
        assert np.asarray(g).dtype == np.asarray(w).dtype
        assert _bits(g) == _bits(w)


# The three migration directions the acceptance criteria name.  Plans
# use min_size=1 so every leaf — the 12-element bf16 bias included —
# actually relayouts instead of staying replicated.
_PAIRS = {
    "shrink": ({"fsdp": 4}, fsdp_plan(min_size=1),
               {"fsdp": 2}, fsdp_plan(min_size=1)),
    "grow": ({"fsdp": 2}, fsdp_plan(min_size=1),
             {"fsdp": 4}, fsdp_plan(min_size=1)),
    "axis_reshape": ({"fsdp": 4}, fsdp_plan(min_size=1),
                     {"fsdp": 2, "tp": 2}, gspmd_2d_plan(min_size=1)),
}


class TestOfflineReshard:
    @pytest.mark.parametrize("pair", sorted(_PAIRS))
    def test_bitwise_roundtrip(self, tmp_path, pair):
        axes_a, plan_a, axes_b, plan_b = _PAIRS[pair]
        mesh_a, mesh_b = _mesh(axes_a), _mesh(axes_b)
        base = _state()
        src = tmp_path / "src"
        save_checkpoint(src, _shard(base, plan_a, mesh_a))

        dst = reshard.reshard_checkpoint(src, plan_b, mesh_b, tmp_path / "dst")
        ok, reason = verify_checkpoint(dst)
        assert ok, reason
        ok, reason = reshard.verify_reshard(src, dst)
        assert ok, reason

        # The destination is a NORMAL checkpoint: plain restore with a
        # plan-B target returns the exact original values.
        restored = restore_checkpoint(dst, target=_shard(base, plan_b, mesh_b))
        _assert_bitwise(restored, base)
        # ... laid out as plan B says, not plan A.
        k = restored["params"]["dense"]["kernel"]
        assert k.sharding == plan_b.sharding_for(
            "params.dense.kernel", k.shape, mesh_b)

    def test_topology_block_written_and_digest_stable(self, tmp_path):
        mesh = _mesh({"fsdp": 4})
        state = _shard(_state(), fsdp_plan(min_size=1), mesh)
        save_checkpoint(tmp_path / "ck", state)
        topo = read_manifest(tmp_path / "ck")["topology"]
        assert topo["mesh_axes"] == {"fsdp": 4}
        assert topo["specs"]["params.dense.kernel"] == spec_str(
            fsdp_plan(min_size=1).spec_for("params.dense.kernel", (8, 12), mesh))
        assert topo["plan_digest"] == plan_digest(
            topo["mesh_axes"], topo["specs"])
        assert state_topology(state) == topo

    def test_old_manifest_without_topology_still_verifies(self, tmp_path,
                                                          monkeypatch):
        # Simulate a checkpoint written by PRE-topology code: the save
        # path records no topology block (editing the manifest after the
        # fact would break the commit marker's checksum — by design).
        from torchdistx_tpu.utils import checkpoint as ckpt
        monkeypatch.setattr(ckpt, "state_topology", lambda state: None)
        mesh = _mesh({"fsdp": 2})
        state = _shard(_state(), fsdp_plan(min_size=1), mesh)
        save_checkpoint(tmp_path / "ck", state)
        monkeypatch.undo()
        man = json.loads((tmp_path / "ck" / "tdx_manifest.json").read_text())
        assert "topology" not in man
        ok, reason = verify_checkpoint(tmp_path / "ck")
        assert ok, reason
        # No topology record -> no opinion -> plain restore path.
        assert reshard.needs_reshard(tmp_path / "ck", state) is False
        restored = restore_checkpoint(tmp_path / "ck", target=state)
        _assert_bitwise(restored, _state())

    def test_reshard_refuses_uncommitted_source(self, tmp_path):
        (tmp_path / "junk").mkdir()
        with pytest.raises(ReshardError):
            reshard.reshard_checkpoint(
                tmp_path / "junk", fsdp_plan(min_size=1), _mesh({"fsdp": 2}))

    def test_plan_describes_schedule_and_byte_totals(self, tmp_path):
        mesh_a = _mesh({"fsdp": 4})
        save_checkpoint(tmp_path / "ck",
                        _shard(_state(), fsdp_plan(min_size=1), mesh_a))
        pl = reshard.plan_reshard(
            tmp_path / "ck", gspmd_2d_plan(min_size=1), _mesh({"fsdp": 2, "tp": 2}))
        names = {t.name for t in pl.leaves}
        assert "params.dense.kernel" in names
        assert "opt.0.mu.dense.kernel" in names  # optimizer state planned too
        assert pl.total_bytes == sum(t.nbytes for t in pl.leaves)
        text = pl.describe()
        assert "params.dense.kernel" in text and "chunks" in text

    def test_target_mesh_can_be_device_free_meshspec(self, tmp_path):
        # The offline path is pure tensorstore: planning AND applying
        # work against a MeshSpec, no accelerator runtime needed
        # (tools/reshard_ctl.py relies on this).
        mesh_a = _mesh({"fsdp": 4})
        base = _state()
        save_checkpoint(tmp_path / "src", _shard(base, fsdp_plan(min_size=1), mesh_a))
        dst = reshard.reshard_checkpoint(
            tmp_path / "src", fsdp_plan(min_size=1),
            reshard.MeshSpec({"fsdp": 2}), tmp_path / "dst")
        restored = restore_checkpoint(
            dst, target=_shard(base, fsdp_plan(min_size=1), _mesh({"fsdp": 2})))
        _assert_bitwise(restored, base)


class TestMemoryBound:
    def test_transfer_peak_bounded_by_chunk_budget(self, tmp_path):
        # One leaf far over the budget: 1 MiB of float32 against a
        # 16 KiB chunk budget.  The tracked host-staging peak must stay
        # within 2x the budget (transfer stages one chunk; the bitwise
        # verify double-buffers source + destination chunks).
        mesh_a, mesh_b = _mesh({"fsdp": 4}), _mesh({"fsdp": 2})
        big = {"w": jnp.arange(262144, dtype=jnp.float32).reshape(1024, 256),
               "step": jnp.int32(0)}
        save_checkpoint(tmp_path / "src",
                        _shard(big, fsdp_plan(min_size=1), mesh_a))
        chunk_mb = 16 / 1024  # 16 KiB
        budget = int(chunk_mb * (1 << 20))
        assert big["w"].nbytes > budget  # the leaf genuinely exceeds it

        dst = reshard.reshard_checkpoint(
            tmp_path / "src", fsdp_plan(min_size=1), mesh_b,
            tmp_path / "dst", chunk_mb=chunk_mb)
        peak = reshard.last_transfer_peak_bytes()
        assert 0 < peak <= 2 * budget

        restored = restore_checkpoint(
            dst, target=_shard(big, fsdp_plan(min_size=1), mesh_b))
        _assert_bitwise(restored, big)

    def test_online_peak_respects_env_budget(self, tmp_path):
        from torchdistx_tpu import config as tdx_config

        mesh_a, mesh_b = _mesh({"fsdp": 4}), _mesh({"fsdp": 2})
        big = {"w": jnp.arange(131072, dtype=jnp.float32).reshape(512, 256),
               "step": jnp.int32(0)}
        save_checkpoint(tmp_path / "src",
                        _shard(big, fsdp_plan(min_size=1), mesh_a))
        chunk_mb = 16 / 1024
        budget = int(chunk_mb * (1 << 20))
        with tdx_config.override(reshard_chunk_mb=chunk_mb):
            out = reshard.restore_resharded(
                tmp_path / "src", _shard(big, fsdp_plan(min_size=1), mesh_b))
        _assert_bitwise(out, big)
        assert 0 < reshard.last_transfer_peak_bytes() <= 2 * budget


class TestElasticReshard:
    def _mk(self, mesh):
        sh = NamedSharding(mesh, P("fsdp"))
        return {"w": jax.device_put(jnp.arange(16, dtype=jnp.float32), sh),
                "n": jnp.float32(0.0)}

    @staticmethod
    def _step(state, batch):
        return ({"w": state["w"] * jnp.float32(1.5) + batch,
                 "n": state["n"] + 1}, {})

    def test_needs_reshard_discriminates(self, tmp_path):
        mesh_a, mesh_b = _mesh({"fsdp": 4}), _mesh({"fsdp": 2})
        save_checkpoint(tmp_path / "ck", self._mk(mesh_a))
        assert reshard.needs_reshard(tmp_path / "ck", self._mk(mesh_a)) is False
        assert reshard.needs_reshard(tmp_path / "ck", self._mk(mesh_b)) is True

    def test_resume_onto_smaller_mesh_reshards_in_flight(self, tmp_path):
        mesh_a, mesh_b = _mesh({"fsdp": 4}), _mesh({"fsdp": 2})
        batches = [jnp.float32(i) for i in range(1, 7)]
        out4, steps4, _ = run_elastic(
            self._step, self._mk(mesh_a), batches[:4],
            checkpoint_dir=str(tmp_path), checkpoint_every=2,
            probe_on_restart=False)
        assert steps4 == 4

        before = observe.counters().counter("tdx.reshard.elastic_reshards").value
        out, steps, _ = run_elastic(
            self._step, self._mk(mesh_b), batches,
            checkpoint_dir=str(tmp_path), checkpoint_every=100,
            resume=True, probe_on_restart=False)
        assert steps == 6
        assert observe.counters().counter(
            "tdx.reshard.elastic_reshards").value == before + 1
        # New-mesh layout...
        assert out["w"].sharding.mesh.shape == {"fsdp": 2}
        # ... exact trajectory: bitwise equal to the uninterrupted run.
        ref = self._mk(mesh_a)
        for b in batches:
            ref, _ = self._step(ref, b)
        assert _bits(out["w"]) == _bits(ref["w"])

    def test_resume_onto_larger_mesh_reshards_in_flight(self, tmp_path):
        mesh_a, mesh_b = _mesh({"fsdp": 2}), _mesh({"fsdp": 4})
        batches = [jnp.float32(i) for i in range(1, 5)]
        run_elastic(self._step, self._mk(mesh_a), batches[:2],
                    checkpoint_dir=str(tmp_path), checkpoint_every=2,
                    probe_on_restart=False)
        out, steps, _ = run_elastic(
            self._step, self._mk(mesh_b), batches,
            checkpoint_dir=str(tmp_path), checkpoint_every=100,
            resume=True, probe_on_restart=False)
        assert steps == 4
        assert out["w"].sharding.mesh.shape == {"fsdp": 4}
        ref = self._mk(mesh_a)
        for b in batches:
            ref, _ = self._step(ref, b)
        assert _bits(out["w"]) == _bits(ref["w"])

    def test_same_mesh_resume_skips_reshard(self, tmp_path):
        mesh_a = _mesh({"fsdp": 4})
        batches = [jnp.float32(i) for i in range(1, 4)]
        run_elastic(self._step, self._mk(mesh_a), batches[:2],
                    checkpoint_dir=str(tmp_path), checkpoint_every=2,
                    probe_on_restart=False)
        before = observe.counters().counter("tdx.reshard.elastic_reshards").value
        run_elastic(self._step, self._mk(mesh_a), batches,
                    checkpoint_dir=str(tmp_path), checkpoint_every=100,
                    resume=True, probe_on_restart=False)
        assert observe.counters().counter(
            "tdx.reshard.elastic_reshards").value == before


class TestChaosReshard:
    def _save_src(self, tmp_path, axes={"fsdp": 4}):
        src = tmp_path / "src"
        save_checkpoint(src, _shard(_state(), fsdp_plan(min_size=1), _mesh(axes)))
        return src

    def test_raise_fault_degrades_never_corrupts(self, tmp_path):
        src = self._save_src(tmp_path)
        chaos.install("reshard@2=raise")
        try:
            with pytest.raises(ReshardError):
                reshard.reshard_checkpoint(
                    src, fsdp_plan(min_size=1), _mesh({"fsdp": 2}),
                    tmp_path / "dst")
        finally:
            chaos.clear()
        ok, reason = verify_checkpoint(src)  # source untouched
        assert ok, reason
        assert not (tmp_path / "dst").exists()  # no partial destination
        assert not (tmp_path / "src.corrupt").exists()  # nothing quarantined

    def test_corrupt_fault_caught_by_bitwise_verify(self, tmp_path):
        src = self._save_src(tmp_path)
        before = observe.counters().counter("tdx.reshard.verify_fail").value
        chaos.install("reshard@3=corrupt:flip")
        try:
            with pytest.raises(ReshardError, match="verify"):
                reshard.reshard_checkpoint(
                    src, fsdp_plan(min_size=1), _mesh({"fsdp": 2}),
                    tmp_path / "dst")
        finally:
            chaos.clear()
        assert observe.counters().counter(
            "tdx.reshard.verify_fail").value == before + 1
        ok, reason = verify_checkpoint(src)
        assert ok, reason
        assert not (tmp_path / "dst").exists()

    def test_slow_fault_completes_exactly(self, tmp_path):
        src = self._save_src(tmp_path)
        base = _state()
        chaos.install("reshard@1=slow:0.01")
        try:
            dst = reshard.reshard_checkpoint(
                src, fsdp_plan(min_size=1), _mesh({"fsdp": 2}), tmp_path / "dst")
        finally:
            chaos.clear()
        restored = restore_checkpoint(
            dst, target=_shard(base, fsdp_plan(min_size=1), _mesh({"fsdp": 2})))
        _assert_bitwise(restored, base)

    def test_online_corrupt_detected_and_typed(self, tmp_path):
        src = self._save_src(tmp_path)
        plan = chaos.parse_plan("reshard@2=corrupt:flip")
        with pytest.raises(ReshardError):
            reshard.restore_resharded(
                src, _shard(_state(), fsdp_plan(min_size=1), _mesh({"fsdp": 2})),
                chaos_plan=plan)
        ok, reason = verify_checkpoint(src)
        assert ok, reason

    def test_elastic_reshard_failure_does_not_quarantine(self, tmp_path):
        """A ReshardError inside _restore_best must surface typed — not
        be swallowed by the quarantine fallback (the source checkpoint
        is fine; it is the TRANSFER that failed)."""
        mesh_a, mesh_b = _mesh({"fsdp": 4}), _mesh({"fsdp": 2})
        sh = NamedSharding(mesh_a, P("fsdp"))
        state = {"w": jax.device_put(jnp.arange(16, dtype=jnp.float32), sh)}
        run_elastic(lambda s, b: ({"w": s["w"] + b}, {}), state,
                    [jnp.float32(1.0)], checkpoint_dir=str(tmp_path),
                    checkpoint_every=1, probe_on_restart=False)
        chaos.install("reshard@1=raise")
        try:
            with pytest.raises(ReshardError):
                run_elastic(
                    lambda s, b: ({"w": s["w"] + b}, {}),
                    {"w": jax.device_put(jnp.arange(16, dtype=jnp.float32),
                                         NamedSharding(mesh_b, P("fsdp")))},
                    [jnp.float32(1.0)], checkpoint_dir=str(tmp_path),
                    checkpoint_every=100, resume=True, probe_on_restart=False)
        finally:
            chaos.clear()
        ok, reason = verify_checkpoint(tmp_path / "step_1")
        assert ok, reason  # the good checkpoint was NOT quarantined


class TestAutoExecutor:
    def test_explicit_spellings_unchanged(self):
        from torchdistx_tpu.parallel import pipeline
        assert pipeline._resolve_executor("segmented", total_ticks=4) == "segmented"
        assert pipeline._resolve_executor("uniform", total_ticks=400) == "uniform"
        with pytest.raises(ValueError, match="bogus"):
            pipeline._resolve_executor("bogus")

    def test_auto_picks_by_schedule_and_host_size(self, monkeypatch):
        from torchdistx_tpu.parallel import pipeline
        monkeypatch.setattr(pipeline.os, "cpu_count", lambda: 8)
        assert pipeline._resolve_executor("auto", total_ticks=8) == "uniform"
        assert pipeline._resolve_executor("auto", total_ticks=64) == "segmented"
        monkeypatch.setattr(pipeline.os, "cpu_count", lambda: 64)
        # A big host amortizes segmented compile even on tiny schedules.
        assert pipeline._resolve_executor("auto", total_ticks=8) == "segmented"

    def test_env_spelling_routes_through_auto(self, monkeypatch):
        from torchdistx_tpu.parallel import pipeline
        monkeypatch.setenv("TDX_PP_EXECUTOR", "auto")
        monkeypatch.setattr(pipeline.os, "cpu_count", lambda: 4)
        assert pipeline._resolve_executor(None, total_ticks=6) == "uniform"


_SHRINK_PHASE1 = """
import os, sys, time
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
from torchdistx_tpu.parallel.mesh import make_mesh
from torchdistx_tpu.utils.failures import run_elastic

d = sys.argv[1]
mesh = make_mesh({"fsdp": 4}, devices=jax.devices()[:4])
state = {"w": jax.device_put(jnp.arange(32, dtype=jnp.float32),
                             NamedSharding(mesh, P("fsdp")))}

def stepf(state, batch):
    time.sleep(0.1)
    return {"w": state["w"] * jnp.float32(1.25) + batch}, {}

batches = [jnp.float32(i) for i in range(1, 41)]
with open(os.path.join(d, "started"), "w") as f:
    f.write("1")
run_elastic(stepf, state, batches, checkpoint_dir=d, checkpoint_every=2,
            exit_on_drain=True)
print("RAN-TO-COMPLETION")
"""

_SHRINK_PHASE2 = """
import json, os, sys
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
from torchdistx_tpu import reshard
from torchdistx_tpu.parallel.mesh import make_mesh
from torchdistx_tpu.parallel.sharding import fsdp_plan
from torchdistx_tpu.utils.checkpoint import restore_checkpoint
from torchdistx_tpu.utils.failures import run_elastic

d, total = sys.argv[1], int(sys.argv[2])
mesh = make_mesh({"fsdp": 2}, devices=jax.devices()[:2])
sh = NamedSharding(mesh, P("fsdp"))
mk = lambda: {"w": jax.device_put(jnp.arange(32, dtype=jnp.float32), sh)}

def stepf(state, batch):
    return {"w": state["w"] * jnp.float32(1.25) + batch}, {}

batches = [jnp.float32(i) for i in range(1, total + 1)]
out, steps, _ = run_elastic(stepf, mk(), batches, checkpoint_dir=d,
                            checkpoint_every=1000, resume=True,
                            probe_on_restart=False)
assert steps == total, (steps, total)

# Reference trajectory: offline-reshard the drained checkpoint to the
# 2-way layout, restore it plainly, and run the remaining steps without
# the elastic loop.
drained = json.load(open(os.path.join(d, "CLEAN_EXIT.json")))["step"]
src = os.path.join(d, "step_%d" % drained)
dst = reshard.reshard_checkpoint(src, fsdp_plan(min_size=1), mesh)
ref = restore_checkpoint(str(dst), target=mk())
for b in batches[drained:]:
    ref, _ = stepf(ref, b)
rb = np.asarray(ref["w"]).view(np.uint8).tobytes()
ob = np.asarray(out["w"]).view(np.uint8).tobytes()
assert rb == ob, "elastic-resharded trajectory diverged from reference"
print("TRAJECTORY-BITWISE-EQUAL steps=%d drained=%d" % (steps, drained))
"""


@pytest.mark.slow
class TestMeshShrinkMidTraining:
    """The ISSUE's chaos scenario: SIGTERM-drain a 4-way run, restore the
    drain checkpoint onto a 2-way mesh via the elastic reshard path in a
    FRESH process, and pin the continued trajectory bitwise against an
    uninterrupted 2-way run from the resharded state."""

    def test_sigterm_drain_then_resume_on_half_mesh(self, tmp_path):
        env = {**os.environ, "JAX_PLATFORMS": "cpu",
               "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH", "")}
        s1 = tmp_path / "phase1.py"
        s1.write_text(_SHRINK_PHASE1)
        proc = subprocess.Popen(
            [sys.executable, str(s1), str(tmp_path)], env=env, cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        try:
            deadline = time.time() + 120
            started = tmp_path / "started"
            while not started.exists():
                assert proc.poll() is None, proc.communicate()[1]
                assert time.time() < deadline, "phase 1 never reached the loop"
                time.sleep(0.05)
            time.sleep(0.5)  # a few steps in
            proc.send_signal(signal.SIGTERM)
            out, err = proc.communicate(timeout=90)
        finally:
            if proc.poll() is None:
                proc.kill()
        assert proc.returncode == 0, err
        assert "RAN-TO-COMPLETION" not in out
        drained = json.loads((tmp_path / "CLEAN_EXIT.json").read_text())["step"]
        assert 1 <= drained < 40

        s2 = tmp_path / "phase2.py"
        s2.write_text(_SHRINK_PHASE2)
        res = subprocess.run(
            [sys.executable, str(s2), str(tmp_path), "40"], env=env, cwd=REPO,
            capture_output=True, text=True, timeout=300)
        assert res.returncode == 0, res.stderr
        assert "TRAJECTORY-BITWISE-EQUAL" in res.stdout
