"""Round-trip tests for serializable recordings (serialize.py).

The reference cannot do this at all (in-memory graph of type-erased
closures, SURVEY.md §5); these tests pin down the semantics that make the
capability real: torch replay equivalence, jax-bridge RNG equivalence
(key_nr preservation), alias/in-place graph fidelity, and error surfaces.
"""

import numpy as np
import pytest
import torch

from torchdistx_tpu.deferred_init import deferred_init, materialize_tensor
from torchdistx_tpu.fake import is_fake
from torchdistx_tpu.jax_bridge import materialize_params_jax
from torchdistx_tpu.serialize import load_recording, save_recording


def _roundtrip(fakes, tmp_path):
    p = tmp_path / "rec.tdx"
    save_recording(fakes, p)
    return load_recording(p)


class TestTorchReplay:
    def test_factory_chain(self, tmp_path):
        t = deferred_init(lambda: (torch.ones(4, 3) * 2).add_(1))
        loaded = _roundtrip({"t": t}, tmp_path)["t"]
        assert is_fake(loaded) and loaded.shape == (4, 3)
        real = materialize_tensor(loaded)
        assert torch.equal(real, torch.full((4, 3), 3.0))
        # the original recording is untouched by save/load
        assert torch.equal(materialize_tensor(t), real)

    def test_rng_replay_matches(self, tmp_path):
        # Replay consumes the *replay-time* global RNG (seeding at record
        # time is a no-op on the recording — same as the reference, whose
        # replay uses the live ThreadLocalState). Same seed at both replay
        # sites -> identical values.
        t = deferred_init(lambda: torch.empty(32).uniform_())
        loaded = _roundtrip({"t": t}, tmp_path)["t"]
        torch.manual_seed(7)
        a = materialize_tensor(loaded)
        torch.manual_seed(7)
        b = materialize_tensor(t)
        assert torch.equal(a, b)

    def test_in_place_through_view(self, tmp_path):
        def make():
            w = torch.ones(4, 3)
            w[2].add_(5)  # view + in-place: the hard graph semantics
            return w

        t = deferred_init(make)
        loaded = _roundtrip({"t": t}, tmp_path)["t"]
        real = materialize_tensor(loaded)
        expect = torch.ones(4, 3)
        expect[2] += 5
        assert torch.equal(real, expect)

    def test_external_tensor_argument(self, tmp_path):
        ext = torch.arange(6, dtype=torch.float32).reshape(2, 3)
        t = deferred_init(lambda: torch.zeros(2, 3).add_(ext))
        loaded = _roundtrip({"t": t}, tmp_path)["t"]
        assert torch.equal(materialize_tensor(loaded), ext)

    def test_mutating_external_after_save_is_safe(self, tmp_path):
        # The file embeds a copy semantically: mutating the live tensor
        # afterwards must not corrupt (or block) the loaded replay.
        ext = torch.ones(3)
        t = deferred_init(lambda: torch.zeros(3).add_(ext))
        p = tmp_path / "rec.tdx"
        save_recording({"t": t}, p)
        ext.mul_(99)
        loaded = load_recording(p)["t"]
        assert torch.equal(materialize_tensor(loaded), torch.ones(3))

    def test_parameter_class_preserved(self, tmp_path):
        m = deferred_init(torch.nn.Linear, 4, 2)
        loaded = _roundtrip(m, tmp_path)
        w = materialize_tensor(loaded["weight"])
        assert isinstance(w, torch.nn.Parameter)
        assert w.requires_grad


class TestModuleRoundTrip:
    def test_module_manifest_names(self, tmp_path):
        m = deferred_init(torch.nn.Linear, 4, 2)
        loaded = _roundtrip(m, tmp_path)
        assert set(loaded) == {"weight", "bias"}
        assert loaded["weight"].shape == (2, 4)

    def test_torch_replay_matches_eager(self, tmp_path):
        build = lambda: torch.nn.Sequential(
            torch.nn.Embedding(16, 8, padding_idx=0), torch.nn.Linear(8, 4)
        )
        m = deferred_init(build)
        loaded = _roundtrip(m, tmp_path)
        torch.manual_seed(0)
        eager_sd = build().state_dict()
        # Replay in manifest (== construction) order under the same seed:
        # the RNG stream matches eager construction draw for draw.
        torch.manual_seed(0)
        for name, fake in loaded.items():
            real = materialize_tensor(fake)
            assert torch.equal(real, eager_sd[name]), name

    def test_jax_materialize_matches_original(self, tmp_path):
        m = deferred_init(torch.nn.Linear, 8, 4)
        p = tmp_path / "rec.tdx"
        save_recording(m, p)
        orig = materialize_params_jax(
            {n: f for n, f in [("weight", m.weight), ("bias", m.bias)]}, seed=5
        )
        loaded = load_recording(p)
        again = materialize_params_jax(loaded, seed=5)
        for k in orig:
            assert np.array_equal(np.asarray(orig[k]), np.asarray(again[k])), k

    def test_hf_model_roundtrip(self, tmp_path):
        transformers = pytest.importorskip("transformers")
        cfg = transformers.GPT2Config(
            n_layer=1, n_head=2, n_embd=32, vocab_size=128, n_positions=32
        )
        m = deferred_init(transformers.GPT2LMHeadModel, cfg)
        loaded = _roundtrip(m, tmp_path)
        params = materialize_params_jax(loaded, seed=0)
        assert params["transformer.wte.weight"].shape == (128, 32)
        assert all(np.isfinite(np.asarray(v)).all() for v in params.values())


class TestErrors:
    def test_materialized_recording_rejected(self, tmp_path):
        t = deferred_init(lambda: torch.ones(3).mul_(2))
        materialize_tensor(t, retain_context=True)
        with pytest.raises(ValueError, match="materialized"):
            save_recording({"t": t}, tmp_path / "x.tdx")

    def test_non_fake_rejected(self, tmp_path):
        with pytest.raises(ValueError, match="not fake"):
            save_recording({"t": torch.ones(3)}, tmp_path / "x.tdx")

    def test_unrecorded_fake_rejected(self, tmp_path):
        from torchdistx_tpu.fake import fake_mode

        with fake_mode():
            t = torch.ones(3)
        with pytest.raises(ValueError, match="no recording"):
            save_recording({"t": t}, tmp_path / "x.tdx")

    def test_loaded_recordings_are_read_only(self, tmp_path):
        # Extending a loaded graph with new in-place/view ops cannot alias-
        # track correctly (file-local storage keys), so it must refuse
        # loudly instead of replaying wrong values.
        t = deferred_init(lambda: torch.ones(4, 3))
        loaded = _roundtrip({"t": t}, tmp_path)["t"]
        with pytest.raises(RuntimeError, match="read-only|loaded recording"):
            deferred_init(lambda: loaded[2].add_(5))

    def test_mutated_external_rejected_at_save(self, tmp_path):
        # Saving must enforce the same version-counter guarantee replay
        # does — not launder an unreplayable recording into a file.
        ext = torch.ones(3)
        t = deferred_init(lambda: torch.zeros(3).add_(ext))
        ext.mul_(99)
        with pytest.raises(RuntimeError, match="modified in place"):
            save_recording({"t": t}, tmp_path / "x.tdx")

    def test_size_argument_roundtrips_as_size(self):
        from torchdistx_tpu.serialize import _decode, _encode

        tensors = []
        enc = _encode(torch.Size([2, 3]), tensors)
        assert enc == {"__tdx__": "size", "v": [2, 3]}
        assert isinstance(_decode(enc, tensors), torch.Size)

    def test_bad_file_rejected(self, tmp_path):
        p = tmp_path / "junk.pt"
        torch.save({"something": 1}, p)
        with pytest.raises(ValueError, match="not a torchdistx_tpu recording"):
            load_recording(p)


class TestSessionIsolation:
    def test_load_during_active_session_keeps_key_nrs(self, tmp_path):
        """Loading a recording while a deferred-init session is recording
        must not consume the session's key_nr counter (ADVICE r1: loaded
        nodes shifted every later op's RNG key, silently changing
        parameter values)."""
        p = tmp_path / "rec.tdx"
        seed_t = deferred_init(lambda: torch.empty(4).normal_())
        save_recording({"x": seed_t}, p)

        def make(load):
            a = torch.empty(8)
            a.normal_()
            if load:
                load_recording(p)  # happens mid-session
            b = torch.empty(8)
            b.normal_()
            return a, b

        ref_a, ref_b = deferred_init(make, False)
        got_a, got_b = deferred_init(make, True)
        ref = materialize_params_jax({"a": ref_a, "b": ref_b}, seed=3)
        got = materialize_params_jax({"a": got_a, "b": got_b}, seed=3)
        assert np.array_equal(np.asarray(ref["a"]), np.asarray(got["a"]))
        assert np.array_equal(np.asarray(ref["b"]), np.asarray(got["b"]))


class TestTlsRoundTrip:
    def test_autocast_tls_roundtrips(self, tmp_path):
        """A recording made under torch.autocast must replay identically
        after save/load (Op.tls is part of the v2 format)."""
        import torch.nn as nn
        from torchdistx_tpu.deferred_init import materialize_tensor

        def make():
            with torch.autocast("cpu"):
                return torch.mm(torch.ones(4, 4), torch.ones(4, 4))

        t = deferred_init(make)
        assert t.dtype == torch.bfloat16
        p = tmp_path / "ac.tdx"
        save_recording({"t": t}, p)
        loaded = load_recording(p)
        out = materialize_tensor(loaded["t"])
        assert out.dtype == torch.bfloat16
        assert torch.equal(out, torch.full((4, 4), 4.0, dtype=torch.bfloat16))

    def test_set_data_synthetic_op_roundtrips(self, tmp_path):
        import torch.nn as nn
        from torchdistx_tpu.deferred_init import materialize_module

        class M(nn.Module):
            def __init__(self):
                super().__init__()
                self.lin = nn.Linear(3, 3, bias=False)
                self.lin.weight.data = torch.full((3, 3), 1.25)

        m = deferred_init(M)
        p = tmp_path / "sd.tdx"
        save_recording(m, p)
        loaded = load_recording(p)
        from torchdistx_tpu.deferred_init import materialize_tensor

        name = next(iter(loaded))
        w = materialize_tensor(loaded[name])
        assert torch.equal(w, torch.full((3, 3), 1.25))


def test_noncontiguous_root_geometry_survives_roundtrip(tmp_path):
    # The out_geom field (jax bridge storage-order adapter for dense-but-
    # permuted roots) must survive save/load: without it a loaded
    # recording of a deepcopied transposed op-output would materialize
    # scrambled through the bridge.
    import copy

    import numpy as np

    from torchdistx_tpu.jax_bridge import materialize_params_jax

    def build():
        a = torch.arange(12, dtype=torch.float32).reshape(2, 6)
        b = a.transpose(0, 1).abs().add(3.0)
        return (copy.deepcopy(b),)

    eager = build()[0]
    fakes = deferred_init(build)
    p = tmp_path / "rec.tdx"
    save_recording({"0": fakes[0]}, p)
    loaded = load_recording(p)
    arr = materialize_params_jax(loaded, seed=0)["0"]
    assert np.array_equal(eager.numpy(), np.asarray(arr))
