"""The functorch / ``torch.func`` / ``torch.compile`` interplay story.

The reference documents a hard incompatibility: "functorch and fake
tensors cannot be used in the same process" (reference
src/cc/torchdistx/fake.h:25-29) — its C++ dispatch-key hijack and
functorch's dynamic-layer stack fight over the same dispatcher slots.
This build's fake engine is a ``__torch_dispatch__`` wrapper subclass +
``TorchDispatchMode`` (fake.py), which composes with the functorch
interpreter stack instead of racing it.  These tests pin that claim
(VERDICT r3 missing #2): every scenario below either works, with fakes
flowing through the transform, or raises a clear error we document in
docs/fake_tensor.md §torch.func.
"""

import pytest
import torch
import torch.nn as nn

from torchdistx_tpu.deferred_init import (
    deferred_init,
    materialize_module,
)
from torchdistx_tpu.fake import fake_mode, is_fake


class TestTorchFunc:
    def test_func_on_real_tensors_after_fake_use(self):
        # The reference's documented limitation: fake tensors and
        # functorch in ONE PROCESS.  Here: record fakes, then use
        # torch.func on real tensors — both fine.
        with fake_mode():
            f = torch.ones(3, 3)
        assert is_fake(f)
        x = torch.randn(4, 3)
        g = torch.func.grad(lambda t: (t * t).sum())(x)
        assert torch.allclose(g, 2 * x)
        s = torch.func.vmap(lambda t: t.sum())(x)
        assert s.shape == (4,)

    def test_vmap_over_fake(self):
        # The transform runs THROUGH the fake: shapes propagate, the
        # result is itself fake (meta-backed), nothing materializes.
        with fake_mode():
            f = torch.ones(3, 5)
        r = torch.func.vmap(lambda t: t.sum())(f)
        assert is_fake(r) and r.shape == (3,)

    def test_grad_inside_fake_mode(self):
        with fake_mode():
            y = torch.func.grad(lambda t: (t * t).sum())(torch.ones(3))
        assert is_fake(y) and y.shape == (3,)

    def test_vmap_grad_composition_over_fake(self):
        with fake_mode():
            f = torch.ones(4, 3)
        r = torch.func.vmap(torch.func.grad(lambda t: (t * t).sum()))(f)
        assert is_fake(r) and r.shape == (4, 3)

    def test_functional_call_on_deferred_module(self):
        # torch.func.functional_call with the module's OWN fake params:
        # a shape-level dry run of the forward with no storage.
        m = deferred_init(nn.Linear, 4, 8)
        with fake_mode():
            x = torch.randn(2, 4)
        out = torch.func.functional_call(
            m, dict(m.named_parameters()), (x,)
        )
        assert is_fake(out) and out.shape == (2, 8)


class TestTorchCompile:
    def test_compile_after_materialize(self):
        m = materialize_module(deferred_init(nn.Linear, 4, 8))
        cm = torch.compile(m)
        x = torch.randn(2, 4)
        out = cm(x)
        assert not is_fake(out)
        assert torch.allclose(out, m(x), atol=1e-6)

    def test_compile_on_deferred_then_materialize(self):
        # torch.compile of a still-deferred module: dynamo traces (or
        # graph-breaks to eager), the forward stays fake end-to-end, and
        # the module still materializes to real parameters afterwards —
        # the recording is not corrupted by dynamo's introspection.
        import warnings

        m = deferred_init(nn.Linear, 4, 8)
        cm = torch.compile(m)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")  # dynamo's fallback warning
            out = cm(torch.randn(2, 4))
        assert is_fake(out) and out.shape == (2, 8)
        mm = materialize_module(m)
        assert not is_fake(mm.weight) and mm.weight.shape == (8, 4)
        real = mm(torch.randn(2, 4))
        assert not is_fake(real)
