"""tools/registry_ctl.py tests (ISSUE 7 satellite): ls/verify/gc/stats
over a registry directory, with the age+atime GC sweep and the
verify-quarantine path agreeing with the store's own verification
rule."""

import json
import os
import sys
import time

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

import registry_ctl  # noqa: E402
from torchdistx_tpu.registry import ArtifactRegistry  # noqa: E402


def _publish(root, key, payload=b"x" * 64, name="deadbeef-cache", meta=None):
    reg = ArtifactRegistry(str(root))
    assert reg.publish(key, {name: payload}, meta or {"program_fp": "fp-" + key})
    return reg


def _run(capsys, *argv):
    rc = registry_ctl.main(list(argv))
    out = capsys.readouterr().out.strip().splitlines()[-1]
    return rc, json.loads(out)


def _age(root, key, days, *, atime_days=None):
    """Back-date an entry's publish stamp and file times."""
    edir = os.path.join(str(root), key)
    t = time.time() - days * 86400
    meta_path = os.path.join(edir, "meta.json")
    with open(meta_path) as f:
        doc = json.load(f)
    doc["created"] = t
    with open(meta_path, "w") as f:
        json.dump(doc, f)
    at = time.time() - (atime_days if atime_days is not None else days) * 86400
    for name in os.listdir(edir):
        os.utime(os.path.join(edir, name), (at, t))


def test_ls_and_stats(tmp_path, capsys):
    _publish(tmp_path, "a" * 40)
    _publish(tmp_path, "b" * 40, payload=b"y" * 128)
    rc, out = _run(capsys, "ls", str(tmp_path))
    assert rc == 0 and out["n"] == 2
    by_key = {r["key"]: r for r in out["entries"]}
    assert by_key["b" * 40]["bytes"] == 128
    assert by_key["a" * 40]["program_fp"] == "fp-" + "a" * 40
    assert all(r["complete"] for r in out["entries"])

    rc, st = _run(capsys, "stats", str(tmp_path))
    assert rc == 0
    assert st["entries"] == 2 and st["bytes"] == 192
    assert st["corrupt"] == 0 and st["incomplete"] == 0


def test_verify_flags_and_quarantines_corruption(tmp_path, capsys):
    _publish(tmp_path, "a" * 40)
    _publish(tmp_path, "b" * 40)
    victim = tmp_path / ("b" * 40) / "deadbeef-cache"
    victim.write_bytes(b"z" * 64)  # same size, wrong CRC

    rc, out = _run(capsys, "verify", str(tmp_path))
    assert rc == 1
    assert out["checked"] == 2 and out["failed"] == 1
    assert out["bad"][0]["key"] == "b" * 40
    assert out["quarantined"] == 0  # report-only without the flag

    rc, out = _run(capsys, "verify", str(tmp_path), "--quarantine")
    assert rc == 1 and out["quarantined"] == 1
    assert (tmp_path / ("b" * 40 + ".corrupt")).is_dir()
    # The survivor verifies clean now.
    rc, out = _run(capsys, "verify", str(tmp_path))
    assert rc == 0 and out["checked"] == 1 and out["failed"] == 0


def test_verify_matches_store_fetch_verdict(tmp_path, capsys):
    """ctl's verification rule == the store's: what ctl flags, a fetch
    would quarantine; what ctl passes, a fetch serves."""
    reg = _publish(tmp_path, "a" * 40)
    assert reg.fetch("a" * 40) is not None
    rc, _ = _run(capsys, "verify", str(tmp_path))
    assert rc == 0
    (tmp_path / ("a" * 40) / "deadbeef-cache").write_bytes(b"q")
    rc, _ = _run(capsys, "verify", str(tmp_path))
    assert rc == 1
    assert reg.fetch("a" * 40) is None  # quarantined by the fetch too


def test_gc_age_and_atime_sweep(tmp_path, capsys):
    """Old AND idle entries are swept; old-but-recently-read and fresh
    entries survive — age alone never evicts a hot artifact."""
    _publish(tmp_path, "a" * 40)                      # fresh
    _publish(tmp_path, "b" * 40)                      # old + idle -> dead
    _publish(tmp_path, "c" * 40)                      # old but hot -> kept
    _age(tmp_path, "b" * 40, days=40)
    _age(tmp_path, "c" * 40, days=40, atime_days=0.5)

    rc, out = _run(capsys, "gc", str(tmp_path), "--max-age-days", "30",
                   "--min-atime-days", "7", "--dry-run")
    assert rc == 0 and out["dry_run"] is True
    assert out["removed"] == ["b" * 40]
    assert (tmp_path / ("b" * 40)).is_dir()  # dry run touched nothing

    rc, out = _run(capsys, "gc", str(tmp_path), "--max-age-days", "30",
                   "--min-atime-days", "7")
    assert out["swept"] == 1 and out["kept"] == 2
    assert not (tmp_path / ("b" * 40)).is_dir()
    assert (tmp_path / ("a" * 40)).is_dir()
    assert (tmp_path / ("c" * 40)).is_dir()


def test_gc_sweeps_corrupt_and_stale_tmp(tmp_path, capsys):
    _publish(tmp_path, "a" * 40)
    # A quarantined entry and a torn publish from a dead publisher.
    corrupt = tmp_path / ("d" * 40 + ".corrupt")
    corrupt.mkdir()
    (corrupt / "junk").write_bytes(b"j")
    stale_tmp = tmp_path / ".tmp-pub-dead-1-2"
    stale_tmp.mkdir()
    old = time.time() - 2 * 86400
    os.utime(stale_tmp, (old, old))
    fresh_tmp = tmp_path / ".tmp-pub-live-3-4"
    fresh_tmp.mkdir()

    rc, out = _run(capsys, "gc", str(tmp_path), "--max-age-days", "30")
    assert rc == 0
    assert out["corrupt_removed"] == 1 and out["tmp_removed"] == 1
    assert not corrupt.is_dir()
    assert not stale_tmp.is_dir()
    assert fresh_tmp.is_dir()  # a live publisher may still own it

    # --keep-corrupt preserves forensics.
    corrupt.mkdir()
    rc, out = _run(capsys, "gc", str(tmp_path), "--max-age-days", "30",
                   "--keep-corrupt")
    assert out["corrupt_removed"] == 0 and corrupt.is_dir()


def test_verify_does_not_defeat_gc_idle_test(tmp_path, capsys):
    """A cron'd verify full-reads payloads; it must restore atime so
    old-and-idle entries still gc — verification is not 'use'."""
    _publish(tmp_path, "a" * 40)
    _age(tmp_path, "a" * 40, days=40)
    rc, _ = _run(capsys, "verify", str(tmp_path))
    assert rc == 0
    rc, out = _run(capsys, "gc", str(tmp_path), "--max-age-days", "30",
                   "--min-atime-days", "7")
    assert out["swept"] == 1, out


def test_gc_keeps_entry_on_transient_manifest_error(tmp_path, capsys):
    """A manifest that EXISTS but cannot be read this cycle (stale NFS
    handle, EIO) must never be swept as a torn publish — only a
    genuinely absent meta.json qualifies."""
    edir = tmp_path / ("f" * 40)
    edir.mkdir()
    (edir / "payload-cache").write_bytes(b"p")
    # meta.json exists but open() raises (a directory): the transient-
    # error shape, as seen by _entries.
    (edir / "meta.json").mkdir()
    old = time.time() - 40 * 86400
    for p in (edir, edir / "payload-cache", edir / "meta.json"):
        os.utime(p, (old, old))
    rc, out = _run(capsys, "gc", str(tmp_path), "--max-age-days", "30",
                   "--min-atime-days", "7")
    assert rc == 0
    assert out["swept"] == 0 and out["kept"] == 1
    assert edir.is_dir()


def test_verify_never_quarantines_on_transient_manifest_error(tmp_path,
                                                              capsys):
    """verify --quarantine must not destroy a live entry whose manifest
    merely failed to READ this cycle (one NFS hiccup + cron'd verify +
    gc of .corrupt dirs would otherwise permanently delete a published
    artifact); a manifest that parses as garbage IS quarantined."""
    edir = tmp_path / ("f" * 40)
    edir.mkdir()
    (edir / "payload-cache").write_bytes(b"p")
    (edir / "meta.json").mkdir()  # exists, open() raises → transient shape
    rc, out = _run(capsys, "verify", str(tmp_path), "--quarantine")
    assert rc == 0  # nothing FAILED — one entry skipped
    assert out["skipped_io"] == 1 and out["failed"] == 0
    assert edir.is_dir() and not (tmp_path / ("f" * 40 + ".corrupt")).exists()

    bdir = tmp_path / ("g" * 40)
    bdir.mkdir()
    (bdir / "payload-cache").write_bytes(b"p")
    (bdir / "meta.json").write_text("{not json")  # real corruption
    rc, out = _run(capsys, "verify", str(tmp_path), "--quarantine")
    assert rc == 1 and out["failed"] == 1 and out["quarantined"] == 1
    assert (tmp_path / ("g" * 40 + ".corrupt")).is_dir()


def test_gc_sweeps_torn_incomplete_entries(tmp_path, capsys):
    """A manifest-less entry dir older than the tmp horizon is a torn
    publish that never renamed — swept; stats counts it meanwhile."""
    _publish(tmp_path, "a" * 40)
    torn = tmp_path / ("e" * 40)
    torn.mkdir()
    (torn / "payload").write_bytes(b"p")
    old = time.time() - 2 * 86400
    for p in (torn, torn / "payload"):
        os.utime(p, (old, old))
    rc, st = _run(capsys, "stats", str(tmp_path))
    assert st["incomplete"] == 1
    rc, out = _run(capsys, "gc", str(tmp_path), "--max-age-days", "30")
    assert out["swept"] == 1
    assert not torn.is_dir()
