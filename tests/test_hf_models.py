"""End-to-end coverage for HF model families: the four named in
BASELINE.json (GPT-2, Llama, Mixtral, T5) plus eleven more architectures
(encoder-only, encoder-decoder, vision, audio, multimodal dual-tower,
alibi/rope/learned-position decoder variants) — deferred_init →
{torch replay with eager bitwise parity, JAX materialize}.
"""

import numpy as np
import pytest
import torch

from torchdistx_tpu.deferred_init import deferred_init, materialize_module
from torchdistx_tpu.fake import is_fake
from torchdistx_tpu.jax_bridge import materialize_module_jax
from torchdistx_tpu.parallel import fsdp_plan, make_mesh


def _newer_cases():
    """The families only newer transformers releases provide; raises
    ImportError as a unit when the installed release predates them."""
    from transformers import (
        BloomConfig,
        BloomForCausalLM,
        CLIPConfig,
        CLIPModel,
        CLIPTextConfig,
        CLIPVisionConfig,
        FalconConfig,
        FalconForCausalLM,
        GemmaConfig,
        GemmaForCausalLM,
        GPTNeoXConfig,
        GPTNeoXForCausalLM,
        OPTConfig,
        OPTForCausalLM,
        PhiConfig,
        PhiForCausalLM,
        Qwen2Config,
        Qwen2ForCausalLM,
    )

    return {
        "gpt_neox": (
            GPTNeoXForCausalLM,
            GPTNeoXConfig(hidden_size=64, num_hidden_layers=2,
                          num_attention_heads=4, intermediate_size=128,
                          vocab_size=256),
        ),
        "falcon": (
            FalconForCausalLM,
            FalconConfig(hidden_size=64, num_hidden_layers=2,
                         num_attention_heads=4, vocab_size=256),
        ),
        "clip": (  # dual-tower multimodal: two embeddings + logit_scale scalar
            CLIPModel,
            CLIPConfig.from_text_vision_configs(
                CLIPTextConfig(hidden_size=64, num_hidden_layers=2,
                               num_attention_heads=2, vocab_size=256,
                               intermediate_size=128),
                CLIPVisionConfig(hidden_size=64, num_hidden_layers=2,
                                 num_attention_heads=2, image_size=32,
                                 patch_size=8, intermediate_size=128),
            ),
        ),
        "gemma": (
            GemmaForCausalLM,
            GemmaConfig(hidden_size=64, num_hidden_layers=2,
                        num_attention_heads=4, num_key_value_heads=2,
                        intermediate_size=128, vocab_size=256, head_dim=16),
        ),
        "qwen2": (
            Qwen2ForCausalLM,
            Qwen2Config(hidden_size=64, num_hidden_layers=2,
                        num_attention_heads=4, num_key_value_heads=2,
                        intermediate_size=128, vocab_size=256),
        ),
        "phi": (
            PhiForCausalLM,
            PhiConfig(hidden_size=64, num_hidden_layers=2,
                      num_attention_heads=4, intermediate_size=128,
                      vocab_size=256),
        ),
        "opt": (
            OPTForCausalLM,
            OPTConfig(hidden_size=64, num_hidden_layers=2,
                      num_attention_heads=4, ffn_dim=128, vocab_size=256,
                      word_embed_proj_dim=64),
        ),
        "bloom": (
            BloomForCausalLM,
            BloomConfig(hidden_size=64, n_layer=2, n_head=4, vocab_size=256),
        ),
    }


def _cases():
    from transformers import (
        GPT2Config,
        GPT2LMHeadModel,
        LlamaConfig,
        LlamaForCausalLM,
        MixtralConfig,
        MixtralForCausalLM,
        T5Config,
        T5ForConditionalGeneration,
    )

    from transformers import (
        BertConfig,
        BertModel,
        ViTConfig,
        ViTModel,
        WhisperConfig,
        WhisperModel,
    )

    try:
        newer = _newer_cases()
    except ImportError:
        # Newer architectures absent on older transformers: their
        # families are simply not offered (tests skip via NEWER_FAMILIES
        # guards); the baseline families below stay unaffected.
        newer = {}

    from transformers import (
        GPTJConfig,
        GPTJModel,
        Wav2Vec2Config,
        Wav2Vec2Model,
    )

    stress = {
        # weight_norm parametrization + grouped conv + the legacy
        # torch.Tensor(n) ctor (whose C-side __new__ returns an
        # already-built fake that Python then re-__init__s)
        "wav2vec2": (
            Wav2Vec2Model,
            Wav2Vec2Config(
                hidden_size=64, num_hidden_layers=2, num_attention_heads=2,
                intermediate_size=128, conv_dim=(32, 32), conv_kernel=(3, 3),
                conv_stride=(2, 2), num_feat_extract_layers=2,
                num_conv_pos_embeddings=16, num_conv_pos_embedding_groups=4,
                vocab_size=64,
            ),
        ),
        "gptj": (
            GPTJModel,
            GPTJConfig(n_embd=64, n_layer=2, n_head=4, vocab_size=256,
                       rotary_dim=16),
        ),
    }
    try:
        from transformers import MambaConfig, MambaModel

        # SSM family: einsum-parameterized mixer, expm1/softplus dt init
        stress["mamba"] = (
            MambaModel,
            MambaConfig(hidden_size=64, num_hidden_layers=2, state_size=8,
                        vocab_size=256),
        )
    except ImportError:
        pass

    return {
        **newer,
        **stress,
        "gpt2": (GPT2LMHeadModel, GPT2Config(n_layer=2, n_embd=64, n_head=4, vocab_size=256)),
        "bert": (
            BertModel,
            BertConfig(hidden_size=64, num_hidden_layers=2, num_attention_heads=2,
                       intermediate_size=128, vocab_size=256),
        ),
        "vit": (  # trunc_normal_ rejection sampling: pins the RNG-order
            # alignment of control-flow-forced early materialization
            ViTModel,
            ViTConfig(hidden_size=64, num_hidden_layers=2, num_attention_heads=2,
                      intermediate_size=128, image_size=32, patch_size=8),
        ),
        "whisper": (
            WhisperModel,
            WhisperConfig(d_model=64, encoder_layers=2, decoder_layers=2,
                          encoder_attention_heads=2, decoder_attention_heads=2,
                          encoder_ffn_dim=128, decoder_ffn_dim=128, vocab_size=256,
                          pad_token_id=0, bos_token_id=1, eos_token_id=2,
                          decoder_start_token_id=1, max_source_positions=64,
                          max_target_positions=64),
        ),
        "llama": (
            LlamaForCausalLM,
            LlamaConfig(
                hidden_size=64, intermediate_size=128, num_hidden_layers=2,
                num_attention_heads=4, num_key_value_heads=2, vocab_size=256,
            ),
        ),
        "mixtral": (
            MixtralForCausalLM,
            MixtralConfig(
                hidden_size=64, intermediate_size=128, num_hidden_layers=2,
                num_attention_heads=4, num_key_value_heads=2, vocab_size=256,
                num_local_experts=4,
            ),
        ),
        "t5": (
            T5ForConditionalGeneration,
            T5Config(d_model=64, d_ff=128, num_layers=2, num_heads=4, vocab_size=256, d_kv=16),
        ),
    }


@pytest.mark.parametrize("name", ["gpt2", "llama", "mixtral", "t5"])
def test_deferred_then_torch_replay(name):
    cls, cfg = _cases()[name]
    torch.manual_seed(0)
    m = deferred_init(cls, cfg)
    assert all(is_fake(p) for p in m.parameters())
    materialize_module(m)
    x = torch.randint(0, 256, (1, 8))
    out = m(input_ids=x, decoder_input_ids=x) if name == "t5" else m(x)
    assert out.logits.shape == (1, 8, 256)
    assert torch.isfinite(out.logits).all()


@pytest.mark.parametrize(
    "name", ["gpt2", "llama", "mixtral", "t5", "vit", "whisper"]
)
def test_deferred_then_jax_materialize_sharded(name):
    # vit/whisper extend the sharded path beyond text: conv patch stems
    # and encoder-decoder audio layouts shard through the same
    # size-based plan.
    cls, cfg = _cases()[name]
    m = deferred_init(cls, cfg)
    mesh = make_mesh({"fsdp": 4, "tp": 2})
    params = materialize_module_jax(m, mesh=mesh, plan=fsdp_plan(min_size=512), seed=0)
    assert params
    for k, v in params.items():
        assert np.isfinite(np.asarray(v)).all(), k
    assert any(
        not getattr(v.sharding, "is_fully_replicated", True)
        for v in params.values()
    ), "no parameter actually sharded"


def test_eager_parity_llama():
    cls, cfg = _cases()["llama"]
    torch.manual_seed(0)
    eager = cls(cfg)
    torch.manual_seed(0)
    deferred = deferred_init(cls, cfg)
    materialize_module(deferred)
    for (n1, p1), (n2, p2) in zip(eager.named_parameters(), deferred.named_parameters()):
        assert torch.equal(p1, p2), n1


EXTRA_FAMILIES = [
    "bert", "vit", "whisper", "gpt_neox", "falcon", "clip", "gemma",
    "qwen2", "phi", "opt", "bloom", "wav2vec2", "gptj", "mamba",
]


@pytest.mark.parametrize("name", EXTRA_FAMILIES)
def test_eager_parity_extra_families(name):
    # ViT in particular: HF's trunc_normal_ idiom is rejection sampling
    # with data-dependent loops; parity requires control-flow-forced
    # early materialization to replay pending RNG draws in recorded
    # order (_graph.flush_pending_rng).
    cases = _cases()
    if name not in cases:
        pytest.skip("family requires a newer transformers release")
    cls, cfg = cases[name]
    torch.manual_seed(5)
    eager = cls(cfg)
    torch.manual_seed(5)
    deferred = deferred_init(cls, cfg)
    materialize_module(deferred)
    for (n1, p1), (n2, p2) in zip(
        eager.state_dict().items(), deferred.state_dict().items(), strict=True
    ):
        assert n1 == n2
        assert torch.equal(p1, p2), n1


@pytest.mark.parametrize("name", EXTRA_FAMILIES)
def test_extra_families_jax_materialize(name):
    cases = _cases()
    if name not in cases:
        pytest.skip("family requires a newer transformers release")
    cls, cfg = cases[name]
    m = deferred_init(cls, cfg)
    params = materialize_module_jax(m, seed=0)
    for k, v in params.items():
        assert np.isfinite(np.asarray(v)).all(), k


class TestHFConvenience:
    """torchdistx_tpu.hf — the from_config wrappers (SURVEY §7)."""

    def test_causal_lm_end_to_end(self):
        import numpy as np
        from transformers import GPT2Config

        from torchdistx_tpu.fake import is_fake
        from torchdistx_tpu.hf import deferred_init_from_config, materialize_sharded
        from torchdistx_tpu.parallel import make_mesh

        m = deferred_init_from_config(
            GPT2Config(n_layer=2, n_embd=64, n_head=2, vocab_size=256)
        )
        assert all(is_fake(p) for p in m.parameters())
        mesh = make_mesh({"fsdp": 4, "tp": 2})
        params = materialize_sharded(m, mesh, seed=0, min_shard_size=1024)
        w = np.asarray(params["transformer.wte.weight"])
        assert np.isfinite(w).all() and w.std() > 0
        assert any(
            not getattr(v.sharding, "is_fully_replicated", True)
            for v in params.values()
        )

    def test_seq2seq_auto_cls(self):
        from transformers import AutoModelForSeq2SeqLM, T5Config

        from torchdistx_tpu.fake import is_fake
        from torchdistx_tpu.hf import deferred_init_from_config

        m = deferred_init_from_config(
            T5Config(d_model=32, d_ff=64, num_layers=1, num_heads=2,
                     vocab_size=128, d_kv=16),
            auto_cls=AutoModelForSeq2SeqLM,
        )
        assert all(is_fake(p) for p in m.parameters())
