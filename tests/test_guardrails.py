"""Fleet-guardrail tests (ISSUE 15 tentpole): circuit breakers with
quarantine-and-respawn, end-to-end deadlines with mid-decode lane
cancellation, hedged dispatch, and priority brownout — all preserving
the fleet oracle gate: every request that completes is bitwise-equal to
the unbatched ``oracle_generate``; every request that does not carries
exactly ONE typed rejection; no KV page leaks after a storm."""

import threading
import time
from collections import Counter

import numpy as np
import pytest

import jax.numpy as jnp

import torchdistx_tpu.config as tdx_config
from torchdistx_tpu import chaos, observe
from torchdistx_tpu.chaos.plan import Fault, parse_plan
from torchdistx_tpu.models import TransformerConfig
from torchdistx_tpu.serve import (
    AdmissionQueue,
    Brownout,
    CircuitBreaker,
    FleetConfig,
    FleetRejected,
    GuardrailConfig,
    QuarantineEntry,
    Request,
    ServeConfig,
    ServeFleet,
    oracle_generate,
    should_hedge,
    spin_up_replica,
)
from torchdistx_tpu.serve.router import REJECT_REASONS

LLAMA = TransformerConfig(
    vocab_size=128, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
    d_ff=64, max_seq_len=64, dtype=jnp.float32,
)
SCFG = ServeConfig(max_batch=2, page_size=8, n_pages=16,
                   max_pages_per_seq=3, prefill_buckets=(8, 16))


@pytest.fixture(scope="module")
def shared_cache(tmp_path_factory):
    """One persistent compile cache for every fleet in this module (same
    rationale as tests/test_fleet.py: measure guardrail behavior, not
    compile time)."""
    d = str(tmp_path_factory.mktemp("guardrail_cache"))
    import os

    old = os.environ.get("TDX_CACHE_MIN_COMPILE_S")
    os.environ["TDX_CACHE_MIN_COMPILE_S"] = "0"
    yield d
    if old is None:
        os.environ.pop("TDX_CACHE_MIN_COMPILE_S", None)
    else:
        os.environ["TDX_CACHE_MIN_COMPILE_S"] = old


def _fleet(**fc_kw):
    fc_kw.setdefault("stall_s", 60.0)
    return ServeFleet(LLAMA, family="llama", serve_cfg=SCFG,
                      fleet_cfg=FleetConfig(**fc_kw))


def _check_oracle(fl, reqs, out):
    for r in reqs:
        want, want_logits = oracle_generate(
            fl.family, fl.cfg, fl.params, r.tokens, r.max_new_tokens,
            r.eos_id,
        )
        assert out[r.rid] == want, (r.rid, out[r.rid], want)
        np.testing.assert_allclose(
            fl.final_logits[r.rid], want_logits, atol=1e-4,
            err_msg=f"final logits of {r.rid}",
        )


def _csnap():
    return {r["name"]: r["value"] for r in observe.counters().snapshot()
            if r["type"] == "counter"}


# ---------------------------------------------------------------------------
# the flap fault kind (pure plan semantics)
# ---------------------------------------------------------------------------


def test_flap_fires_on_bresenham_duty_cycle_and_is_never_spent():
    plan = parse_plan("fleet@2=flap:0.3")
    fired = [bool(plan.take("fleet", 2)) for _ in range(10)]
    # int(h·duty) increments at hits 4, 7, 10 — exactly ⌊10·0.3⌋ fires,
    # deterministically spread.
    assert fired == [False, False, False, True, False, False, True,
                     False, False, True]
    assert plan.pending() and bool(plan)  # never consumed
    assert plan.take("fleet", 1) == []    # wrong replica: no match
    # duty 1.0 fires on every match; the default duty is 0.5
    always = parse_plan("serve@3=flap:1.0")
    assert all(always.take("serve", 3) for _ in range(5))
    default = parse_plan("serve@1=flap")
    assert [bool(default.take("serve", 1)) for _ in range(4)] == [
        False, True, False, True]


def test_flap_duty_cycle_validation():
    for bad in ("0", "1.5", "-0.2"):
        with pytest.raises(ValueError, match="duty cycle"):
            parse_plan(f"serve@1=flap:{bad}")
    # a direct Fault construction validates too
    with pytest.raises(ValueError, match="duty cycle"):
        Fault("serve", 1, "flap", arg="2.0")


def test_flap_at_serve_site_costs_a_replay_not_a_token(shared_cache):
    """At the engine's ``serve`` site a flap is a retryable step fault:
    the batch requeues (recompute preemption) and regenerates bitwise;
    the plan entry stays armed afterwards."""
    with tdx_config.override(cache_dir=shared_cache):
        eng = spin_up_replica(LLAMA, family="llama", serve_cfg=SCFG)
        chaos.install("serve@2=flap:1.0")
        try:
            reqs = [Request("sf0", [3, 4], max_new_tokens=5),
                    Request("sf1", [9, 1], max_new_tokens=4)]
            out = eng.run(reqs)
            plan = chaos.active_plan()
            assert plan.fired, "flap never fired"
            assert plan.pending(), "flap must never be spent"
        finally:
            chaos.clear()
        for r in reqs:
            want, _ = oracle_generate("llama", LLAMA, eng.params, r.tokens,
                                      r.max_new_tokens, r.eos_id)
            assert out[r.rid] == want
        assert eng.kv.pages_in_use == 0


# ---------------------------------------------------------------------------
# guardrail policies (pure)
# ---------------------------------------------------------------------------


def test_guardrail_config_validation():
    for kw in (dict(breaker_window_s=0.0), dict(breaker_trip_faults=0),
               dict(quarantine_s=0.0), dict(quarantine_s=5.0,
                                            quarantine_max_s=1.0),
               dict(hedge_wait_frac=-0.1),
               dict(brownout_enter_consecutive=0),
               dict(brownout_exit_consecutive=0)):
        with pytest.raises(ValueError):
            GuardrailConfig(**kw)


def test_circuit_breaker_sliding_window():
    gc = GuardrailConfig(breaker_trip_faults=3, breaker_window_s=10.0)
    b = CircuitBreaker(gc)
    b.record(0.0, "flap")
    b.record(1.0, "flap")
    assert not b.tripped(2.0)
    b.record(2.0, "slow")
    assert b.tripped(2.0)
    # observations age out of the window: only t=2.0 survives at t=11.5
    assert b.count(11.5) == 1
    assert not b.tripped(11.5)


def test_quarantine_backoff_doubles_and_caps():
    gc = GuardrailConfig(quarantine_s=2.0, quarantine_max_s=6.0)
    q = QuarantineEntry(origin_idx=2, until=2.0, backoff_s=2.0, probe_idx=5)
    q.fail_probe(10.0, gc)
    assert (q.backoff_s, q.until, q.probe_idx) == (4.0, 14.0, None)
    q.fail_probe(20.0, gc)
    assert q.backoff_s == 6.0  # capped
    q.fail_probe(30.0, gc)
    assert q.backoff_s == 6.0


def test_quarantine_backoff_explicit_cap():
    """``backoff_cap_s`` pins the doubling ceiling independently of the
    quarantine residency bound: the backoff clamps at the cap while
    ``quarantine_max_s`` stays free to bound how long an entry may sit
    quarantined overall."""
    gc = GuardrailConfig(quarantine_s=2.0, quarantine_max_s=60.0,
                         backoff_cap_s=5.0)
    q = QuarantineEntry(origin_idx=1, until=2.0, backoff_s=2.0, probe_idx=3)
    q.fail_probe(10.0, gc)
    assert (q.backoff_s, q.probe_idx) == (4.0, None)
    q.fail_probe(20.0, gc)
    assert q.backoff_s == 5.0  # capped by backoff_cap_s, not 60s
    q.fail_probe(30.0, gc)
    assert (q.backoff_s, q.until) == (5.0, 35.0)
    with pytest.raises(ValueError):
        GuardrailConfig(backoff_cap_s=0.0)


def test_brownout_hysteresis():
    gc = GuardrailConfig(brownout_queue_per_replica=4.0,
                         brownout_enter_consecutive=2,
                         brownout_exit_consecutive=2)
    bo = Brownout(gc)
    assert not bo.observe(queued=9, serving=2)   # pressure streak 1
    assert not bo.observe(queued=0, serving=2)   # a dip resets the streak
    assert not bo.observe(queued=9, serving=2)
    assert bo.observe(queued=9, serving=2)       # sustained → enter
    assert bo.observe(queued=0, serving=2)       # still active: exit streak 1
    assert not bo.observe(queued=0, serving=2)   # exit
    # zero serving replicas is an availability problem, not load pressure
    assert not Brownout(gc).observe(queued=100, serving=0)
    # the latency signal works alone
    lat = Brownout(GuardrailConfig(brownout_ttft_p95_s=0.5,
                                   brownout_enter_consecutive=1))
    assert lat.observe(queued=0, serving=1, ttft_p95=0.9)


def test_should_hedge_predicate():
    gc = GuardrailConfig(hedge_wait_frac=0.5)
    assert should_hedge(0.6, 1.0, gc)
    assert not should_hedge(0.4, 1.0, gc)
    assert not should_hedge(99.0, None, gc)  # deadline-less: off by default
    assert should_hedge(1.5, None, GuardrailConfig(hedge_wait_s=1.0))
    assert not should_hedge(99.0, 0.1, GuardrailConfig(hedging=False))


# ---------------------------------------------------------------------------
# admission queue: shedding + requeue-ordering property
# ---------------------------------------------------------------------------


def test_shed_low_priority_spares_the_requeue_lane():
    q = AdmissionQueue(max_depth=8)
    q.push(Request("lo1", [1], max_new_tokens=1, priority=0))
    q.push(Request("hi", [1], max_new_tokens=1, priority=1))
    q.push(Request("lo2", [1], max_new_tokens=1, priority=0))
    q.requeue(Request("rq-lo", [1], max_new_tokens=1, priority=0))
    shed = q.shed_low_priority(1)
    assert [r.rid for r in shed] == ["lo1", "lo2"]
    assert all(r.reason == "shed" for r in shed)
    # the requeue lane is exempt (an admitted request is a promise),
    # and still jumps the line
    assert q.pop().req.rid == "rq-lo"
    assert q.pop().req.rid == "hi"
    assert q.pop() is None


def test_requeue_ordering_property_under_concurrent_push_and_expire():
    """The requeue-lane contract under contention: requeues from many
    threads keep their per-thread relative order, are exempt from the
    bound AND the deadline (none lost, none expired), while regular
    pushes concurrently overflow and expire around them."""
    q = AdmissionQueue(max_depth=4)
    n_requeuers, per = 4, 50
    errors = []
    expired = []
    stop = threading.Event()

    def requeuer(t):
        try:
            for i in range(per):
                q.requeue(Request(f"rq-{t}-{i}", [1], max_new_tokens=1))
        except BaseException as e:  # noqa: BLE001 — reraised on the main thread
            errors.append(e)

    def pusher(t):
        try:
            for i in range(per):
                try:
                    q.push(Request(f"push-{t}-{i}", [1], max_new_tokens=1),
                           deadline_s=0.0005)
                except FleetRejected as e:
                    assert e.rejection.reason == "queue_full"
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    def expirer():
        try:
            while not stop.is_set():
                expired.extend(q.expire())
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    workers = [threading.Thread(target=requeuer, args=(t,))
               for t in range(n_requeuers)]
    workers += [threading.Thread(target=pusher, args=(t,)) for t in range(2)]
    exp_t = threading.Thread(target=expirer)
    exp_t.start()
    for w in workers:
        w.start()
    for w in workers:
        w.join()
    stop.set()
    exp_t.join()
    assert not errors, errors
    # flush every remaining fifo entry (they all carry a tiny deadline)
    future = time.monotonic() + 1.0
    expired.extend(q.expire(now=future))
    popped = []
    while True:
        entry = q.pop(now=future)
        if entry is None:
            break
        popped.append(entry.req.rid)
    # every requeue survived the bound, the deadline, and the shedding
    assert len(popped) == n_requeuers * per
    assert all(rid.startswith("rq-") for rid in popped)
    assert all(not r.rid.startswith("rq-") for r in expired), (
        "a requeued entry expired")
    for t in range(n_requeuers):
        mine = [int(rid.split("-")[2]) for rid in popped
                if rid.startswith(f"rq-{t}-")]
        assert mine == list(range(per)), (
            f"thread {t} requeue order perturbed: {mine[:10]}...")


# ---------------------------------------------------------------------------
# engine: mid-decode deadline cancellation
# ---------------------------------------------------------------------------


def test_engine_cancels_doomed_lane_mid_decode(shared_cache):
    """A lane past its end-to-end deadline is cancelled mid-decode: its
    pages go back to the pool immediately, ``on_cancel`` carries the
    tokens generated so far (an oracle prefix), and the surviving lane
    completes bitwise-unperturbed."""
    with tdx_config.override(cache_dir=shared_cache):
        cancelled = []
        eng = spin_up_replica(
            LLAMA, family="llama", serve_cfg=SCFG,
            on_cancel=lambda rid, toks, active: cancelled.append(
                (rid, toks, active)),
        )
        doomed = Request("doomed", [5, 6, 7], max_new_tokens=20)
        keeper = Request("keeper", [9, 8], max_new_tokens=6)
        eng.submit(doomed)
        eng.submit(keeper)
        for _ in range(3):
            eng.step()
        lane = next(ln for ln in eng.active.values()
                    if ln.req.rid == "doomed")
        assert eng.kv.has(lane.seq_id)
        doomed._deadline_t = 0.0  # force: already past its deadline
        eng.step()                # the sweep runs at the top of the step
        assert cancelled == [("doomed", eng.cancelled["doomed"], True)]
        toks = cancelled[0][1]
        assert len(toks) >= 1
        assert not eng.kv.has(lane.seq_id)  # pages freed NOW
        assert all(ln.req.rid != "doomed" for ln in eng.active.values())
        while eng.waiting or eng.active:
            eng.step()
        want, _ = oracle_generate("llama", LLAMA, eng.params, keeper.tokens,
                                  keeper.max_new_tokens, keeper.eos_id)
        assert eng.results["keeper"] == want
        # the delivered-so-far tokens are an exact oracle prefix
        dwant, _ = oracle_generate("llama", LLAMA, eng.params, doomed.tokens,
                                   doomed.max_new_tokens, doomed.eos_id)
        assert toks == dwant[:len(toks)]
        assert eng.kv.pages_in_use == 0
        # caller-initiated cancel: waiting request → [], unknown → None
        eng.submit(Request("w", [1, 2], max_new_tokens=2))
        assert eng.cancel("w") == []
        assert eng.cancel("nope") is None


def test_engine_requeue_active_replays_bitwise(shared_cache):
    """``requeue_active`` (the fleet's flap path) preempts every lane
    back to waiting; greedy decode regenerates them identically."""
    with tdx_config.override(cache_dir=shared_cache):
        eng = spin_up_replica(LLAMA, family="llama", serve_cfg=SCFG)
        r = Request("rq", [4, 5], max_new_tokens=5)
        eng.submit(r)
        eng.step()
        assert eng.active
        assert eng.requeue_active() == 1
        assert not eng.active and eng.waiting
        assert eng.kv.pages_in_use == 0  # preempt freed the lane's pages
        out = eng.run()
        want, _ = oracle_generate("llama", LLAMA, eng.params, r.tokens,
                                  r.max_new_tokens, r.eos_id)
        assert out["rq"] == want


# ---------------------------------------------------------------------------
# fleet: flap survival, breaker lifecycle, hedging, brownout, the storm pin
# ---------------------------------------------------------------------------


def test_flap_replica_survives_and_stays_oracle(shared_cache):
    """An intermittent fleet-site fault does NOT kill the replica: the
    batch requeues, the fault lands in the handle's observation deque,
    and output stays oracle-exact (faults cost latency, never a
    token)."""
    gc = GuardrailConfig(breaker=False, hedging=False, brownout=False)
    with tdx_config.override(cache_dir=shared_cache):
        with _fleet(min_replicas=1, max_replicas=1, autoscale=False,
                    guardrails=gc) as fl:
            fl.start(1, timeout=240.0)
            chaos.install("fleet@1=flap:0.5")
            try:
                reqs = [Request(f"fs{i}", [6 + i, 2, 8], max_new_tokens=3,
                                arrival_step=i) for i in range(6)]
                out = fl.run(reqs, max_seconds=240.0)
            finally:
                chaos.clear()
            assert set(out) == {r.rid for r in reqs}
            assert not fl.rejected
            _check_oracle(fl, reqs, out)
            (h,) = fl.handles
            assert h.idx == 1 and h.state == "serving"  # it survived
            # breaker off → observations retained, proving they were made
            assert len(h.faults) >= 1


def test_breaker_lifecycle_trip_quarantine_probe_rejoin(shared_cache):
    """The full breaker arc: a flapping replica trips the breaker, is
    drained (responsive eject) and quarantined; the min-replica floor
    backfills immediately; after the backoff a HALF-OPEN probe replica
    spawns (registry/cache-warm: zero local compiles), completes one
    request cleanly, and is promoted to full rotation — with every
    served request still oracle-exact."""
    gc = GuardrailConfig(breaker_trip_faults=2, breaker_window_s=60.0,
                         quarantine_s=0.05, quarantine_max_s=1.0,
                         hedging=False, brownout=False)
    observe.enable(True)
    try:
        with tdx_config.override(cache_dir=shared_cache):
            with _fleet(min_replicas=2, max_replicas=3, autoscale=False,
                        guardrails=gc) as fl:
                fl.start(2, timeout=240.0)
                base = _csnap()
                chaos.install("fleet@2=flap:1.0")
                sent, i = [], 0
                try:
                    deadline = time.monotonic() + 240.0
                    while True:
                        # Keep 4 requests in flight so replica 2 keeps a
                        # batch to fault on AND the probe replica (last
                        # in dispatch order) actually receives one.  A
                        # few hundred tiny requests flow through before
                        # the arc completes — prompts are drawn from a
                        # 3-element set so the oracle sweep below stays
                        # cheap (oracle_generate retraces per call).
                        while len(fl._pending) < 4 and i < 4000:
                            r = Request(f"bl{i}", [2 + (i % 3), 5, 7],
                                        max_new_tokens=3)
                            fl.submit(r)
                            sent.append(r)
                            i += 1
                        fl.tick()
                        snap = _csnap()
                        probes = (snap.get("tdx.fleet.half_open_probes", 0)
                                  - base.get("tdx.fleet.half_open_probes", 0))
                        if (probes >= 1 and not fl.quarantine
                                and not any(h.half_open
                                            for h in fl.handles)):
                            break  # probe promoted: lifecycle complete
                        assert time.monotonic() < deadline, (
                            fl.quarantine,
                            [(h.idx, h.state, h.half_open)
                             for h in fl.handles])
                        time.sleep(0.001)
                finally:
                    chaos.clear()
                out = fl.run(max_seconds=240.0)  # finish the tail
                assert set(out) == {r.rid for r in sent}
                assert not fl.rejected
                # Bitwise pin on a bounded sample (first and last — the
                # tail was served post-promotion, through the probe era);
                # checking all ~hundreds would just re-pay oracle
                # compiles on identical prompts.
                _check_oracle(fl, sent[:4] + sent[-4:], out)
                snap = _csnap()
                assert (snap.get("tdx.fleet.breaker_trips", 0)
                        - base.get("tdx.fleet.breaker_trips", 0)) >= 1
                # a breaker ejection is not a scaling decision
                assert (snap.get("tdx.fleet.scale_downs", 0)
                        == base.get("tdx.fleet.scale_downs", 0))
                # the flaky replica is gone; the floor kept ≥2 serving
                assert all(h.idx != 2 for h in fl.handles)
                assert sum(1 for h in fl.handles
                           if h.state == "serving") >= 2
                # respawn + probe were warm: zero local compiles after
                # the initial bring-up
                assert (snap.get("tdx.jax.compile_cache_miss", 0)
                        == base.get("tdx.jax.compile_cache_miss", 0))
                assert all(h.bring_up_warm for h in fl.handles
                           if h.idx >= 3)
    finally:
        observe.enable(None)
        observe.health.reset()


def test_hedged_dispatch_first_ttft_wins_bitwise(shared_cache):
    """With the hedge threshold at zero every deadlined dispatch races
    two replicas: first TTFT wins, the loser's lane is cancelled and its
    pages freed — and the client-visible stream carries each oracle
    token exactly once."""
    gc = GuardrailConfig(breaker=False, brownout=False,
                         hedging=True, hedge_wait_frac=0.0)
    observe.enable(True)
    seen = {}
    try:
        with tdx_config.override(cache_dir=shared_cache):
            fl = ServeFleet(
                LLAMA, family="llama", serve_cfg=SCFG,
                fleet_cfg=FleetConfig(min_replicas=2, max_replicas=2,
                                      autoscale=False, stall_s=60.0,
                                      guardrails=gc),
                on_token=lambda rid, tok: seen.setdefault(rid, []).append(tok),
            )
            with fl:
                fl.start(2, timeout=240.0)
                base = _csnap()
                reqs = [Request(f"hg{i}", [7 + i, 3], max_new_tokens=12,
                                deadline_s=120.0) for i in range(4)]
                for r in reqs:
                    fl.submit(r)
                deadline = time.monotonic() + 240.0
                while fl._pending:
                    fl.tick()  # tight loop: ticks outpace token arrivals
                    assert time.monotonic() < deadline
                    time.sleep(0.0005)
                out = dict(fl.results)
                assert set(out) == {r.rid for r in reqs}
                assert not fl.rejected
                _check_oracle(fl, reqs, out)
                snap = _csnap()
                assert (snap.get("tdx.fleet.hedged_requests", 0)
                        - base.get("tdx.fleet.hedged_requests", 0)) >= 1
                assert (snap.get("tdx.fleet.hedge_wins", 0)
                        - base.get("tdx.fleet.hedge_wins", 0)) >= 1
                # exactly-once stream: per rid, the delivered tokens are
                # the oracle tokens, each exactly once (dedupe suppressed
                # the loser's copies)
                for r in reqs:
                    assert Counter(seen[r.rid]) == Counter(out[r.rid]), r.rid
                # the losers' lanes were cancelled, pages reclaimed
                for h in fl.handles:
                    if h.engine is not None and h.engine.k_pages is not None:
                        assert h.engine.kv.pages_in_use == 0
                assert not fl._hedges and not fl.partial
    finally:
        observe.enable(None)
        observe.health.reset()


def test_brownout_sheds_queued_and_rejects_at_door(shared_cache):
    """Sustained pressure sheds queued low-priority work (typed ``shed``
    rejections), rejects new low-priority work at the door, leaves
    high-priority output oracle-exact, and exits on hysteresis — after
    which low-priority work is admitted again."""
    # queued > 2×serving is pressure: the initial 8-deep burst trips it
    # on the first tick, while the single post-brownout request doesn't
    # re-trip it.
    gc = GuardrailConfig(breaker=False, hedging=False,
                         brownout_queue_per_replica=2.0,
                         brownout_enter_consecutive=1,
                         brownout_exit_consecutive=2,
                         brownout_priority=1)
    observe.enable(True)
    try:
        with tdx_config.override(cache_dir=shared_cache):
            with _fleet(min_replicas=1, max_replicas=1, autoscale=False,
                        guardrails=gc) as fl:
                fl.start(1, timeout=240.0)
                base = _csnap()
                highs = [Request(f"hi{i}", [4 + i, 9], max_new_tokens=3,
                                 priority=1) for i in range(4)]
                lows = [Request(f"lo{i}", [2 + i, 3], max_new_tokens=3,
                                priority=0) for i in range(4)]
                for r in lows + highs:
                    fl.submit(r)
                fl.tick()  # pressure → enter → shed lows → dispatch highs
                assert fl.brownout.active
                for r in lows:
                    assert fl.rejected[r.rid].reason == "shed", r.rid
                with pytest.raises(FleetRejected) as ei:
                    fl.submit(Request("door", [1, 2], max_new_tokens=2,
                                      priority=0))
                assert ei.value.rejection.reason == "shed"
                out = fl.run(max_seconds=240.0)
                assert set(out) == {r.rid for r in highs}
                _check_oracle(fl, highs, out)
                # pressure cleared while the highs drained → hysteresis
                fl.tick()
                fl.tick()
                assert not fl.brownout.active
                late = Request("late-lo", [5, 6], max_new_tokens=2,
                               priority=0)
                fl.submit(late)  # admitted again after the brownout
                out = fl.run(max_seconds=240.0)
                _check_oracle(fl, [late], out)
                snap = _csnap()
                assert (snap.get("tdx.fleet.brownouts", 0)
                        - base.get("tdx.fleet.brownouts", 0)) == 1
                assert (snap.get("tdx.fleet.shed_requests", 0)
                        - base.get("tdx.fleet.shed_requests", 0)) == 5
    finally:
        observe.enable(None)
        observe.health.reset()


def test_guardrail_storm_invariant(shared_cache):
    """THE acceptance pin: a mixed storm — flapping replica, mixed
    priorities, a couple of hopeless deadlines, one invalid request —
    with every guardrail armed.  Every request that completes is
    bitwise-equal to the oracle; every request that does not carries
    exactly one typed rejection; no KV pages leak."""
    gc = GuardrailConfig(breaker_trip_faults=3, breaker_window_s=60.0,
                         quarantine_s=0.2, quarantine_max_s=2.0,
                         hedging=True, hedge_wait_frac=0.9,
                         brownout=True, brownout_queue_per_replica=50.0)
    observe.enable(True)
    try:
        with tdx_config.override(cache_dir=shared_cache):
            with _fleet(min_replicas=2, max_replicas=3, autoscale=False,
                        guardrails=gc) as fl:
                fl.start(2, timeout=240.0)
                chaos.install("fleet@2=flap:0.6")
                try:
                    reqs = []
                    for i in range(16):
                        reqs.append(Request(
                            f"st{i}", [(5 * i + j) % 128
                                       for j in range(2 + i % 5)],
                            max_new_tokens=2 + (i % 4),
                            priority=i % 2,
                            deadline_s=(0.02 if i in (5, 11) else
                                        60.0 if i % 3 == 0 else None),
                            arrival_step=i,
                        ))
                    reqs.append(Request("bad", [], max_new_tokens=2,
                                        arrival_step=3))
                    out = fl.run(reqs, max_seconds=240.0)
                finally:
                    chaos.clear()
                for r in reqs:
                    if r.rid in out:
                        assert r.rid not in fl.rejected, r.rid
                        _check_oracle(fl, [r], out)
                    else:
                        rej = fl.rejected[r.rid]  # exactly one, typed
                        assert rej.reason in REJECT_REASONS, rej
                        if rej.reason == "deadline" and rej.tokens:
                            want, _ = oracle_generate(
                                fl.family, fl.cfg, fl.params, r.tokens,
                                r.max_new_tokens, r.eos_id)
                            assert list(rej.tokens) == want[:len(rej.tokens)]
                assert fl.rejected["bad"].reason == "invalid"
                # no KV pages leak past the storm
                for h in fl.handles:
                    if h.engine is not None and h.engine.k_pages is not None:
                        assert h.engine.kv.pages_in_use == 0, h.idx
                assert not fl.partial and not fl._hedges
    finally:
        observe.enable(None)
        observe.health.reset()
