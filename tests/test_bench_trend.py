"""Bench-trajectory sentinel (tools/bench_trend.py): the trend table
renders the repo's real BENCH_r*.json history without error, regressions
in gated relative/efficiency keys exit 1 against the best COMPARABLE
prior round, absolute wall times never gate (the recorded history
proves they measure the host, not the code), unknown hardware classes
neither gate nor baseline, and truncated rounds are tolerated."""

from __future__ import annotations

import glob
import importlib.util
import json
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def bt():
    spec = importlib.util.spec_from_file_location(
        "bench_trend", os.path.join(REPO, "tools", "bench_trend.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _round(tmp_path, n, parsed):
    p = tmp_path / f"BENCH_r{n:02d}.json"
    p.write_text(json.dumps({"n": n, "rc": 0, "parsed": parsed}))
    return str(p)


_CPU = {"platform": "cpu(fallback: tpu unavailable)", "host_cpu_count": 8}


class TestUnits:
    def test_hw_class_takes_platform_first_token(self, bt):
        assert bt.hw_class({"platform": "cpu(fallback: x)"}) == "cpu"
        assert bt.hw_class({"platform": "tpu (cached v5e)"}) == "tpu"
        assert bt.hw_class({"platform": "CPU"}) == "cpu"
        assert bt.hw_class({}) is None
        assert bt.hw_class({"platform": "   "}) is None
        assert bt.hw_class({"platform": 7}) is None

    def test_comparable_needs_same_class_and_cpu_count(self, bt):
        a = {"platform": "cpu", "host_cpu_count": 8}
        assert bt.comparable(a, {"platform": "cpu(fallback)",
                                 "host_cpu_count": 8})
        assert not bt.comparable(a, {"platform": "tpu", "host_cpu_count": 8})
        assert not bt.comparable(a, {"platform": "cpu", "host_cpu_count": 4})
        # rounds before the cpu-count stamp compare by platform alone
        assert bt.comparable(a, {"platform": "cpu"})
        assert not bt.comparable(a, {})

    def test_gated_keys_are_relative_not_absolute(self, bt):
        assert bt.gate_for("vs_baseline") == ("up", 0.10)
        assert bt.gate_for("decode_vs_baseline") == ("up", 0.20)
        assert bt.gate_for("materialize_gbps") is not None
        assert bt.gate_for("pipeline_speedup") is not None
        assert bt.gate_for("train_mfu") is not None
        assert bt.gate_for("peak_rss_mb") == ("down", 0.15)
        # absolute seconds and counts never gate
        assert bt.gate_for("value") is None
        assert bt.gate_for("baseline_s") is None
        assert bt.gate_for("materialize_s") is None
        assert bt.gate_for("n_programs") is None


class TestRealHistory:
    def test_repo_rounds_render_clean(self, bt, capsys):
        paths = sorted(glob.glob(os.path.join(REPO, "BENCH_r*.json")))
        if not paths:
            pytest.skip("no BENCH rounds in this checkout")
        rc = bt.main(paths)
        out = capsys.readouterr().out
        assert rc == 0, out
        assert "no regressions" in out
        assert f"{len(paths)} round(s)" in out


class TestRegressionGate:
    def test_regressed_ratio_exits_1(self, bt, tmp_path, capsys):
        paths = [
            _round(tmp_path, 1, {**_CPU, "vs_baseline": 1.05, "value": 3.3}),
            _round(tmp_path, 2, {**_CPU, "vs_baseline": 0.5, "value": 3.4}),
        ]
        rc = bt.main(paths)
        out = capsys.readouterr().out
        assert rc == 1
        assert "REGRESSIONS: 1" in out
        assert "r02 vs_baseline" in out
        assert "0.5!" in out  # flagged cell in the table too

    def test_doubled_wall_time_does_not_gate(self, bt, tmp_path, capsys):
        # The real r02→r03 shape: host slowdown doubles `value` while
        # the same-host ratio barely moves — must NOT flag.
        paths = [
            _round(tmp_path, 1, {**_CPU, "vs_baseline": 1.07, "value": 3.3}),
            _round(tmp_path, 2, {**_CPU, "vs_baseline": 1.04, "value": 6.7}),
        ]
        assert bt.main(paths) == 0
        assert "no regressions" in capsys.readouterr().out

    def test_unknown_platform_never_gates_or_baselines(self, bt, tmp_path,
                                                       capsys):
        paths = [
            _round(tmp_path, 1, {**_CPU, "vs_baseline": 2.0}),
            _round(tmp_path, 2, {"vs_baseline": 0.1}),          # no platform
            _round(tmp_path, 3, {**_CPU, "vs_baseline": 1.9}),
        ]
        assert bt.main(paths) == 0
        capsys.readouterr()

    def test_cross_class_rounds_do_not_compare(self, bt, tmp_path, capsys):
        paths = [
            _round(tmp_path, 1, {"platform": "tpu v5e", "vs_baseline": 3.0}),
            _round(tmp_path, 2, {**_CPU, "vs_baseline": 1.0}),
        ]
        assert bt.main(paths) == 0
        capsys.readouterr()

    def test_best_prior_not_latest_is_the_baseline(self, bt, tmp_path,
                                                   capsys):
        # A slow slide: each round is within threshold of the LAST one
        # but far below the BEST one — the gate must catch it.
        paths = [
            _round(tmp_path, 1, {**_CPU, "vs_baseline": 1.00}),
            _round(tmp_path, 2, {**_CPU, "vs_baseline": 0.95}),
            _round(tmp_path, 3, {**_CPU, "vs_baseline": 0.88}),
        ]
        rc = bt.main(paths)
        out = capsys.readouterr().out
        assert rc == 1
        assert "r03 vs_baseline" in out and "r01" in out

    def test_down_direction_gates_rss_growth(self, bt, tmp_path, capsys):
        paths = [
            _round(tmp_path, 1, {**_CPU, "peak_rss_mb": 900.0}),
            _round(tmp_path, 2, {**_CPU, "peak_rss_mb": 1200.0}),
        ]
        rc = bt.main(paths)
        assert rc == 1
        assert "r02 peak_rss_mb" in capsys.readouterr().out


class TestResilience:
    def test_empty_parsed_round_tolerated(self, bt, tmp_path, capsys):
        paths = [
            _round(tmp_path, 1, {**_CPU, "vs_baseline": 1.0}),
            _round(tmp_path, 4, None),  # truncated tail → parsed absent
        ]
        assert bt.main(paths) == 0
        out = capsys.readouterr().out
        assert "r04" in out and "truncated/failed round" in out

    def test_unreadable_file_skipped_with_warning(self, bt, tmp_path,
                                                  capsys):
        good = _round(tmp_path, 1, {**_CPU, "vs_baseline": 1.0})
        bad = tmp_path / "BENCH_r02.json"
        bad.write_text("{truncated")
        assert bt.main([good, str(bad)]) == 0
        assert "skipping" in capsys.readouterr().err

    def test_no_rounds_exits_2(self, bt, tmp_path, capsys):
        assert bt.main([str(tmp_path / "nothing.json")]) == 2
        capsys.readouterr()

    def test_bookkeeping_keys_never_render(self, bt, tmp_path, capsys):
        paths = [_round(tmp_path, 1, {
            **_CPU, "vs_baseline": 1.0, "rc": 0, "n": 3,
            "record_skipped": 1, "cache_age_s": 9.9,
        })]
        assert bt.main(paths) == 0
        out = capsys.readouterr().out
        assert "record_skipped" not in out and "cache_age_s" not in out
