"""Serve-fleet tests (ISSUE 14 tentpole): the multi-replica router +
autoscaler preserves the single-engine oracle contract — token-exact
output through storms, staggered arrivals, replica chaos-kills, and
scale-up/scale-down transitions — while the router stays fair, the
admission queue rejects typed, the autoscaler doesn't flap, drains
complete in-flight work bitwise, and a registry-warm scale-up performs
zero local compiles."""

import shutil
import tempfile
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import torchdistx_tpu.config as tdx_config
from torchdistx_tpu import chaos, observe
from torchdistx_tpu.jax_bridge import materialize as mat
from torchdistx_tpu.models import TransformerConfig
from torchdistx_tpu.serve import (
    AdmissionQueue,
    Autoscaler,
    FleetConfig,
    FleetRejected,
    Request,
    ServeConfig,
    ServeFleet,
    least_outstanding,
    oracle_generate,
    warm_serving,
)

LLAMA = TransformerConfig(
    vocab_size=128, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
    d_ff=64, max_seq_len=64, dtype=jnp.float32,
)
SCFG = ServeConfig(max_batch=2, page_size=8, n_pages=16,
                   max_pages_per_seq=3, prefill_buckets=(8, 16))


@pytest.fixture(scope="module")
def shared_cache(tmp_path_factory):
    """One persistent compile cache for every fleet in this module: the
    first replica compiles the tiny program set, every later replica
    (and every later test) loads it — fleet tests measure fleet
    behavior, not compile time."""
    d = str(tmp_path_factory.mktemp("fleet_cache"))
    import os

    old = os.environ.get("TDX_CACHE_MIN_COMPILE_S")
    os.environ["TDX_CACHE_MIN_COMPILE_S"] = "0"
    yield d
    if old is None:
        os.environ.pop("TDX_CACHE_MIN_COMPILE_S", None)
    else:
        os.environ["TDX_CACHE_MIN_COMPILE_S"] = old


def _check_oracle(fl, reqs, out):
    for r in reqs:
        want, want_logits = oracle_generate(
            fl.family, fl.cfg, fl.params, r.tokens, r.max_new_tokens,
            r.eos_id,
        )
        assert out[r.rid] == want, (r.rid, out[r.rid], want)
        np.testing.assert_allclose(
            fl.final_logits[r.rid], want_logits, atol=1e-4,
            err_msg=f"final logits of {r.rid}",
        )


# ---------------------------------------------------------------------------
# router (pure)
# ---------------------------------------------------------------------------


def test_least_outstanding_routes_by_work_not_count():
    """Fairness under skewed lanes: one 64-token generation must weigh
    more than two 2-token pings — dispatch follows remaining budget."""
    loads = {"a": 64, "b": 4, "c": 9}
    assert least_outstanding(["a", "b", "c"], loads.get) == "b"
    # ties break by listing order (deterministic under test)
    assert least_outstanding(["a", "b"], lambda h: 7) == "a"
    assert least_outstanding([], lambda h: 0) is None


def test_admission_queue_bound_deadline_and_requeue_priority():
    q = AdmissionQueue(max_depth=2)
    q.push(Request("a", [1], max_new_tokens=1))
    q.push(Request("b", [1], max_new_tokens=1))
    with pytest.raises(FleetRejected) as ei:
        q.push(Request("c", [1], max_new_tokens=1))
    assert ei.value.rejection.reason == "queue_full"
    # requeues are exempt from the bound and jump the line
    q.requeue(Request("dead", [1], max_new_tokens=1))
    assert q.depth() == 3
    assert q.pop().req.rid == "dead"
    # a queued entry past its deadline is expired with a typed rejection;
    # the unexpired survivors still pop in FIFO order
    q2 = AdmissionQueue(max_depth=8)
    q2.push(Request("d", [1], max_new_tokens=1), deadline_s=0.001, now=0.0)
    q2.push(Request("e", [1], max_new_tokens=1), now=0.0)
    rejs = q2.expire(now=1.0)
    assert [(r.rid, r.reason) for r in rejs] == [("d", "deadline")]
    assert q2.pop().req.rid == "e"
    assert q2.pop() is None
    # the original queue kept its FIFO intact
    assert q.pop().req.rid == "a"
    assert q.pop().req.rid == "b"
    assert q.pop() is None


# ---------------------------------------------------------------------------
# autoscaler (pure)
# ---------------------------------------------------------------------------


def test_autoscaler_hysteresis_no_flap_on_step_load():
    """A step load change produces exactly one scale-up (streak +
    cooldown), and brief idle dips never drain a replica."""
    fc = FleetConfig(min_replicas=1, max_replicas=4,
                     up_queue_per_replica=2.0, up_consecutive=3,
                     down_consecutive=4, cooldown_s=10.0)
    a = Autoscaler(fc)

    def busy(now, serving, total):
        return a.decide(now=now, queued=10, outstanding=30,
                        serving=serving, total=total)

    assert busy(1.0, 1, 1) is None        # pressure streak 1
    assert busy(2.0, 1, 1) is None        # streak 2
    assert busy(3.0, 1, 1) == "up"        # streak 3 → fire once
    # the step persists but cooldown holds: no flapping
    assert busy(4.0, 2, 2) is None
    assert busy(5.0, 2, 2) is None
    assert busy(6.0, 2, 2) is None
    # past cooldown, SUSTAINED pressure may fire again
    assert busy(14.0, 2, 2) == "up"

    idle = Autoscaler(FleetConfig(min_replicas=1, down_consecutive=4,
                                  cooldown_s=0.0))

    def quiet(now):
        return idle.decide(now=now, queued=0, outstanding=0,
                           serving=2, total=2)

    assert quiet(1.0) is None
    assert quiet(2.0) is None
    assert quiet(3.0) is None
    # one busy tick resets the idle streak — a dip is not a trend
    assert idle.decide(now=4.0, queued=1, outstanding=5,
                       serving=2, total=2) is None
    assert quiet(5.0) is None
    assert quiet(6.0) is None
    assert quiet(7.0) is None
    assert quiet(8.0) == "down"
    # never below the floor / the last replica
    floor = Autoscaler(FleetConfig(min_replicas=1, down_consecutive=1,
                                   cooldown_s=0.0))
    assert floor.decide(now=1.0, queued=0, outstanding=0,
                        serving=1, total=1) is None


def test_autoscaler_backfills_below_floor_even_with_autoscale_off():
    a = Autoscaler(FleetConfig(min_replicas=2, autoscale=False))
    assert a.decide(now=0.0, queued=0, outstanding=0,
                    serving=1, total=1) == "up"
    assert a.decide(now=0.0, queued=99, outstanding=99,
                    serving=2, total=2) is None  # autoscale off


# ---------------------------------------------------------------------------
# health aggregation (pure)
# ---------------------------------------------------------------------------


def test_readyz_fleet_aggregation():
    """fleet/* components aggregate: ready iff ≥1 replica serving, with
    the per-replica states in the probe body."""
    from torchdistx_tpu.observe import health

    health.reset()
    try:
        health.set_state("fleet/r1", "spin_up")
        health.set_state("fleet/r2", "launching")
        ok, detail = health.readiness()
        assert not ok
        assert detail["fleet"]["serving"] == 0
        assert set(detail["fleet"]["replicas"]) == {"r1", "r2"}
        health.set_state("fleet/r2", "serving")
        ok, detail = health.readiness()
        assert ok  # one serving replica is enough
        assert detail["fleet"]["serving"] == 1
        # a non-fleet component still gates individually
        health.set_state("serve", "warming")
        ok, _ = health.readiness()
        assert not ok
        health.clear_state("serve")
        ok, _ = health.readiness()
        assert ok
        # clearing the serving replica flips the fleet back to 503
        health.clear_state("fleet/r2")
        ok, detail = health.readiness()
        assert not ok and detail["not_ready"] == {"fleet": "no replica serving"}
    finally:
        health.reset()


# ---------------------------------------------------------------------------
# the fleet itself
# ---------------------------------------------------------------------------


def _fleet(shared_cache, **fc_kw):
    fc_kw.setdefault("stall_s", 60.0)
    return ServeFleet(LLAMA, family="llama", serve_cfg=SCFG,
                      fleet_cfg=FleetConfig(**fc_kw))


def test_fleet_storm_matches_oracle_across_scale_transitions(shared_cache):
    """The acceptance pin: a staggered storm over 2 replicas with ≥1
    chaos replica-kill, ≥1 scale-up, and ≥1 drain DURING the run — every
    response still equals the unbatched oracle, and the dead replica's
    requests were requeued, not dropped."""
    observe.enable(True)
    try:
        with tdx_config.override(cache_dir=shared_cache):
            with _fleet(shared_cache, min_replicas=1, max_replicas=4,
                        autoscale=False) as fl:
                fl.start(2, timeout=240.0)
                chaos.install("fleet@2=raise")
                reqs = [
                    Request(f"s{i}", [(5 * i + j) % 128 for j in
                                      range(2 + i % 6)],
                            max_new_tokens=4 + (i % 5), arrival_step=i)
                    for i in range(12)
                ]
                did_up = did_down = False
                i = 0
                deadline = time.monotonic() + 240.0
                while i < len(reqs) or fl._pending:
                    while (i < len(reqs)
                           and reqs[i].arrival_step <= fl._tick_no):
                        fl.submit(reqs[i])
                        i += 1
                    fl.tick()
                    serving = sum(1 for h in fl.handles
                                  if h.state == "serving")
                    if not did_up and i >= 6:
                        fl.scale_up()        # ≥1 scale-up mid-run
                        did_up = True
                    if did_up and not did_down and serving > 1 and i >= 10:
                        fl.scale_down()      # ≥1 drain mid-run
                        did_down = True
                    assert time.monotonic() < deadline, (
                        fl._pending, [h.state for h in fl.handles])
                    time.sleep(0.001)
                assert did_up and did_down
                out = dict(fl.results)
                assert set(out) == {r.rid for r in reqs}
                assert not fl.rejected
                _check_oracle(fl, reqs, out)
                snap = {r["name"]: r["value"]
                        for r in observe.counters().snapshot()
                        if r["type"] == "counter"}
                # the chaos kill requeued its mid-batch work
                assert snap.get("tdx.fleet.requeued_requests", 0) >= 1
                assert snap.get("tdx.fleet.scale_ups", 0) >= 3
                assert snap.get("tdx.fleet.scale_downs", 0) >= 1
    finally:
        chaos.clear()
        observe.enable(None)
        observe.health.reset()


@pytest.mark.parametrize("kind", ["raise", "preempt"])
def test_chaos_kill_requeues_onto_survivor(shared_cache, kind):
    """The fleet chaos site kills replica 2 mid-batch (raise = device
    loss, preempt = replica-thread preemption); the survivor regenerates
    every requeued request identically."""
    with tdx_config.override(cache_dir=shared_cache):
        with _fleet(shared_cache, min_replicas=1, max_replicas=2,
                    autoscale=False) as fl:
            fl.start(2, timeout=240.0)
            chaos.install(f"fleet@2={kind}")
            try:
                reqs = [Request(f"k{i}", [3 + i, 7, (11 * i) % 128],
                                max_new_tokens=5, arrival_step=i)
                        for i in range(8)]
                out = fl.run(reqs, max_seconds=240.0)
            finally:
                chaos.clear()
            assert set(out) == {r.rid for r in reqs}
            _check_oracle(fl, reqs, out)
            # replica 2 is gone; the survivor (plus backfill) served
            assert all(h.idx != 2 for h in fl.handles)


def test_drain_completes_inflight_bitwise(shared_cache):
    """Scale-down drains: the draining replica finishes its in-flight
    lanes (bitwise vs oracle), hands back unadmitted work, then frees
    its KV pool."""
    with tdx_config.override(cache_dir=shared_cache):
        with _fleet(shared_cache, min_replicas=1, max_replicas=2,
                    autoscale=False) as fl:
            fl.start(2, timeout=240.0)
            reqs = [Request(f"d{i}", [9 + i, 2, 5], max_new_tokens=12)
                    for i in range(4)]
            for r in reqs:
                fl.submit(r)
            # tick until the fleet actually has lanes in flight
            deadline = time.monotonic() + 60.0
            while not any(h.engine is not None and h.engine.active
                          for h in fl.handles):
                fl.tick()
                assert time.monotonic() < deadline
                time.sleep(0.001)
            h = fl.scale_down()
            assert h is not None
            inflight = {ln.req.rid for ln in list(h.engine.active.values())}
            out = fl.run(max_seconds=240.0)
            assert set(out) == {r.rid for r in reqs}
            _check_oracle(fl, reqs, out)
            # run() returns when the last REQUEST completes, which can
            # beat the victim's drain transition — keep ticking until
            # the controller reaps the drained handle.
            deadline = time.monotonic() + 60.0
            while any(x is h for x in fl.handles):
                fl.tick()
                assert time.monotonic() < deadline, h.state
                time.sleep(0.001)
            assert h.state == "drained"
            assert h.engine.k_pages is None and h.engine.v_pages is None
            # whatever was in flight at drain time completed
            assert inflight <= set(out)


def test_rejection_paths_are_typed_and_counted(shared_cache):
    """Every rejection is typed, recorded, and counted: invalid at the
    door, queue_full at the bound, deadline in the queue."""
    observe.enable(True)
    try:
        fl = ServeFleet(LLAMA, family="llama", serve_cfg=SCFG,
                        fleet_cfg=FleetConfig(min_replicas=0, max_queue=2,
                                              autoscale=False))
        with pytest.raises(FleetRejected) as ei:
            fl.submit(Request("bad", [], max_new_tokens=4))
        assert ei.value.rejection.reason == "invalid"
        # 20 tokens > the largest bucket now serves (chunked prefill);
        # only max_context (3 pages * 8) rejects at the door.
        with pytest.raises(FleetRejected) as ei:
            fl.submit(Request("huge", [1] * 23, max_new_tokens=2))
        assert "max_context" in ei.value.rejection.detail
        fl.submit(Request("q1", [1, 2], max_new_tokens=2))
        fl.submit(Request("q2", [1, 2], max_new_tokens=2))
        with pytest.raises(FleetRejected) as ei:
            fl.submit(Request("q3", [1, 2], max_new_tokens=2))
        assert ei.value.rejection.reason == "queue_full"
        # deadline: no replica will ever pick these up
        fl.queue.drain()
        fl._pending.clear()
        fl.submit(Request("late", [1, 2], max_new_tokens=2),
                  deadline_s=0.001)
        time.sleep(0.02)
        fl.tick()
        assert fl.rejected["late"].reason == "deadline"
        assert {r.reason for r in fl.rejected.values()} == {
            "invalid", "queue_full", "deadline"}
        total = sum(r["value"] for r in observe.counters().snapshot()
                    if r["name"] == "tdx.fleet.rejected_requests")
        assert total >= 4
    finally:
        observe.enable(None)
        observe.health.reset()


def test_hang_stall_declares_replica_dead_and_requeues(shared_cache):
    """A hung replica (chaos ``fleet@1=hang``) stops heartbeating; after
    ``stall_s`` the controller declares it dead, requeues its work onto
    the backfilled replica, and output stays oracle-exact."""
    with tdx_config.override(cache_dir=shared_cache):
        with _fleet(shared_cache, min_replicas=1, max_replicas=2,
                    autoscale=False, stall_s=0.5) as fl:
            fl.start(1, timeout=240.0)
            chaos.install("fleet@1=hang:3600")
            try:
                reqs = [Request(f"h{i}", [2 + i, 4, 6], max_new_tokens=4)
                        for i in range(3)]
                out = fl.run(reqs, max_seconds=240.0)
            finally:
                chaos.clear()
            assert set(out) == {r.rid for r in reqs}
            _check_oracle(fl, reqs, out)
            # the hung r1 was reaped; the backfill served the storm
            assert all(h.idx != 1 for h in fl.handles)


@pytest.mark.slow  # ~15 s of compiles; `make chaos-test` + fleet-smoke run it
def test_scale_up_is_registry_warm_zero_local_compiles(shared_cache):
    """The autoscaling bring-up contract, fleet edition: with a warmed
    registry and a FRESH local cache, every replica the fleet adds —
    initial start and mid-run scale-up — performs ZERO local compiles
    (every program a registry fetch) and still serves oracle-exact."""
    reg = tempfile.mkdtemp(prefix="tdx_fleet_reg_")
    warm_cache = tempfile.mkdtemp(prefix="tdx_fleet_ca_")
    fresh_cache = tempfile.mkdtemp(prefix="tdx_fleet_cb_")
    observe.enable(True)
    try:
        summary = warm_serving("llama", LLAMA, warm_cache,
                               registry_dir=reg, serve_cfg=SCFG)
        assert not summary["unwarmed"], summary
        mat._reset_cache_binding()
        base = {r["name"]: r["value"]
                for r in observe.counters().snapshot()
                if r["type"] == "counter"}
        with tdx_config.override(cache_dir=fresh_cache, registry_dir=reg):
            with ServeFleet(
                LLAMA, family="llama", serve_cfg=SCFG,
                fleet_cfg=FleetConfig(min_replicas=1, max_replicas=2,
                                      autoscale=False),
            ) as fl:
                fl.start(1, timeout=240.0)
                h2 = fl.scale_up(wait=True, timeout=240.0)
                assert h2.bring_up_warm, h2.engine.bring_up_outcomes
                assert set(h2.engine.bring_up_outcomes.values()) == {"hit"}
                snap = {r["name"]: r["value"]
                        for r in observe.counters().snapshot()
                        if r["type"] == "counter"}
                miss = (snap.get("tdx.jax.compile_cache_miss", 0)
                        - base.get("tdx.jax.compile_cache_miss", 0))
                assert miss == 0, [x.engine.bring_up_outcomes
                                   for x in fl.handles]
                assert all(x.bring_up_warm for x in fl.handles)
                reqs = [Request("w", [11, 22, 33], max_new_tokens=4)]
                out = fl.run(reqs, max_seconds=240.0)
                _check_oracle(fl, reqs, out)
    finally:
        observe.enable(None)
        observe.health.reset()
        mat._reset_cache_binding()
        for d in (reg, warm_cache, fresh_cache):
            shutil.rmtree(d, ignore_errors=True)
