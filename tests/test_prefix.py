"""Prefix-sharing tests (ISSUE 16 tentpole): radix-tree prefix cache +
refcounted copy-on-write pages + chunked prefill.

Property layer (no device work): under random interleavings of
admit / extend / retire / preempt / evict / cow, every page's refcount
equals the number of live page tables referencing it plus the number of
prefix-tree nodes holding it; copy-on-write never swaps a page out from
under another reader; a drain leaves every refcount at zero.

Engine layer: a shared-prefix storm is bitwise-equal to the unbatched
oracle with sharing ON and OFF (with prefix hits > 0 in the ON arm);
the fully-cached page-aligned prompt exercises the one legal write into
a shared page through COW; a chaos fault BETWEEN prefill chunks
(``serve@N=raise:chunk``) requeues without leaking pages or corrupting
a shared prefix.
"""

import random
from collections import Counter

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from torchdistx_tpu import chaos, observe
from torchdistx_tpu.models import TransformerConfig
from torchdistx_tpu.serve import (
    KVCacheConfig,
    NgramDrafter,
    OutOfPages,
    PagedKVCache,
    PrefixCache,
    Request,
    ServeConfig,
    ServeEngine,
    oracle_generate,
    prefix_affinity,
    serve_program_specs,
)
from torchdistx_tpu.serve.programs import compile_serving_program

LLAMA = TransformerConfig(
    vocab_size=128, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
    d_ff=64, max_seq_len=64, dtype=jnp.float32,
)
SCFG = ServeConfig(max_batch=2, page_size=8, n_pages=16,
                   max_pages_per_seq=3, prefill_buckets=(8, 16),
                   prefill_chunk=6)


@pytest.fixture(scope="module")
def engine():
    specs = serve_program_specs("llama", LLAMA, SCFG)
    init = specs[0]
    compiled, _ = compile_serving_program(init)
    params = jax.tree.unflatten(init.treedef, list(compiled()))
    eng = ServeEngine("llama", LLAMA, params, serve_cfg=SCFG)
    return eng


def _check_oracle(eng, reqs, out):
    for r in reqs:
        want, _ = oracle_generate(
            eng.family, eng.cfg, eng.params, r.tokens, r.max_new_tokens,
            r.eos_id,
        )
        assert out[r.rid] == want, (r.rid, out[r.rid], want)


# ---------------------------------------------------------------------------
# property layer: refcount bookkeeping under random interleavings
# ---------------------------------------------------------------------------


def _expected_refs(kv: PagedKVCache, tree: PrefixCache) -> Counter:
    want = Counter()
    for sid in list(kv._seqs):
        want.update(kv.page_ids(sid))
    want.update(tree.pages())
    return want


def _assert_refs_consistent(kv: PagedKVCache, tree: PrefixCache) -> None:
    want = _expected_refs(kv, tree)
    have = {p: kv.ref(p) for p in want}
    assert dict(want) == have, (dict(want), have)
    # ...and nothing else holds a count, and the free list + live pages
    # partition the pool exactly (no leak, no double-free).
    assert set(kv._ref) == set(want)
    assert sorted(list(want) + kv._free) == list(
        range(1, kv.cfg.n_pages)), "free list and live pages must partition"


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_refcounts_equal_live_references_under_random_interleaving(seed):
    rng = random.Random(seed)
    cfg = KVCacheConfig(n_layers=1, kv_heads=1, head_dim=4,
                        page_size=4, n_pages=rng.randrange(8, 14))
    kv = PagedKVCache(cfg)
    tree = PrefixCache(kv)
    next_sid = 1
    prompts: dict = {}  # sid -> token list
    for _ in range(400):
        op = rng.random()
        if op < 0.35:  # admit (with sharing when the tree matches)
            toks = [rng.randrange(4) for _ in range(rng.randrange(1, 13))]
            shared = tree.match(toks)
            need = cfg.pages_for(len(toks)) - len(shared)
            if need <= kv.free_pages:
                sid = next_sid
                next_sid += 1
                if shared:
                    kv.alloc_shared(sid, shared, len(toks))
                else:
                    kv.alloc(sid, len(toks))
                prompts[sid] = toks
        elif op < 0.5 and prompts:  # publish a prompt's full blocks
            sid = rng.choice(list(prompts))
            toks = prompts[sid]
            nfull = len(toks) // cfg.page_size
            if nfull:
                tree.insert(toks[:nfull * cfg.page_size],
                            kv.page_ids(sid)[:nfull])
        elif op < 0.65 and prompts:  # grow (decode)
            sid = rng.choice(list(prompts))
            try:
                kv.extend(sid, kv.length(sid) + rng.randrange(1, 4))
            except OutOfPages:
                pass
        elif op < 0.8 and prompts:  # retire / preempt
            sid = rng.choice(list(prompts))
            kv.free(sid)
            del prompts[sid]
        elif op < 0.9:  # evict one LRU cache leaf
            tree.evict()
        elif prompts:  # copy-on-write a random owned page
            sid = rng.choice(list(prompts))
            idx = rng.randrange(len(kv.page_ids(sid)))
            try:
                kv.cow_page(sid, idx)
            except OutOfPages:
                pass
        _assert_refs_consistent(kv, tree)
    # Drain: retire everything, clear the cache — all refcounts zero.
    for sid in list(prompts):
        kv.free(sid)
    tree.clear()
    assert kv.pages_in_use == 0
    assert not kv._ref
    assert len(tree) == 0


@pytest.mark.parametrize("seed", [0, 7])
def test_cow_never_unmaps_a_page_from_other_readers(seed):
    """cow_page moves ONLY the writer's reference: every other table
    that mapped the src page still maps it afterwards, the tree still
    holds it, and the writer gets a fresh private page."""
    rng = random.Random(seed)
    cfg = KVCacheConfig(n_layers=1, kv_heads=1, head_dim=4,
                        page_size=4, n_pages=16)
    kv = PagedKVCache(cfg)
    tree = PrefixCache(kv)
    toks = [1, 2, 3, 4, 5, 6, 7, 8]  # two full pages
    kv.alloc(1, len(toks))
    tree.insert(toks, kv.page_ids(1))
    readers = []
    for sid in range(2, 2 + rng.randrange(1, 4)):
        kv.alloc_shared(sid, tree.match(toks), len(toks))
        readers.append(sid)
    writer = readers[-1]
    idx = rng.randrange(2)
    src = kv.page_ids(writer)[idx]
    before = {sid: kv.page_ids(sid) for sid in [1] + readers[:-1]}
    moved = kv.cow_page(writer, idx)
    assert moved is not None
    s, dst = moved
    assert s == src and dst != src
    assert kv.page_ids(writer)[idx] == dst
    assert kv.ref(dst) == 1
    for sid, pages in before.items():
        assert kv.page_ids(sid) == pages, "readers' tables must not move"
    assert src in tree.pages()
    _assert_refs_consistent(kv, tree)
    # A page owned by exactly one reference needs no copy.
    assert kv.cow_page(writer, idx) is None


def test_tree_match_is_page_aligned_and_lru_evicts_leaves():
    cfg = KVCacheConfig(n_layers=1, kv_heads=1, head_dim=4,
                        page_size=4, n_pages=16)
    kv = PagedKVCache(cfg)
    tree = PrefixCache(kv)
    kv.alloc(1, 10)  # 3 pages: two full blocks + a partial tail
    toks = list(range(10))
    tree.insert(toks, kv.page_ids(1)[:2])
    assert len(tree) == 2
    # Only FULL blocks match; the partial tail never enters the tree.
    assert tree.match(toks) == kv.page_ids(1)[:2]
    assert tree.match(toks[:7]) == kv.page_ids(1)[:1]
    assert tree.match(toks[:3]) == []
    assert tree.match([9] * 8) == []
    assert tree.match_len(toks) == 8
    # A second branch sharing the first block:
    kv.alloc_shared(2, tree.match(toks[:4]), 8)
    branch = toks[:4] + [7, 7, 7, 7]
    tree.insert(branch, kv.page_ids(2))
    assert len(tree) == 3
    kv.free(1)
    kv.free(2)
    # Eviction takes leaves only (LRU): the shared root block must
    # survive until both branches are gone.
    root_page = tree.match(toks[:4])[0]
    assert tree.evict() and len(tree) == 2
    assert tree.evict() and len(tree) == 1
    assert tree.pages() == [root_page]
    assert tree.evict() and len(tree) == 0
    assert not tree.evict()
    assert kv.pages_in_use == 0


def test_rollback_retracts_pages_and_refcounts():
    """Token-level rollback (speculative decoding): the trailing pages a
    shorter length no longer needs return to the free list; a rollback
    that stays within the tail page is bookkeeping only."""
    cfg = KVCacheConfig(n_layers=1, kv_heads=1, head_dim=4,
                        page_size=4, n_pages=16)
    kv = PagedKVCache(cfg)
    kv.alloc(1, 10)                          # 3 pages
    assert kv.rollback(1, 10) == 0           # no-op at the same length
    assert kv.rollback(1, 9) == 0            # same page count, shorter
    assert kv.length(1) == 9
    assert kv.rollback(1, 5) == 1            # drops the third page
    assert len(kv.page_ids(1)) == 2
    assert kv.rollback(1, 0) == 2
    assert kv.page_ids(1) == []
    with pytest.raises(ValueError, match="rollback target"):
        kv.rollback(1, 1)                    # beyond the current length
    with pytest.raises(ValueError, match="rollback target"):
        kv.rollback(1, -1)
    kv.free(1)
    assert kv.pages_in_use == 0
    assert not kv._ref


def test_rollback_on_shared_pages_drops_only_own_reference():
    """Rolling a lane back through COW-shared territory retracts only
    THAT lane's references: the tree and every other reader keep the
    pages, contents untouched."""
    cfg = KVCacheConfig(n_layers=1, kv_heads=1, head_dim=4,
                        page_size=4, n_pages=16)
    kv = PagedKVCache(cfg)
    tree = PrefixCache(kv)
    toks = [1, 2, 3, 4, 5, 6, 7, 8]          # two full pages
    kv.alloc(1, len(toks))
    tree.insert(toks, kv.page_ids(1))
    kv.alloc_shared(2, tree.match(toks), len(toks))
    shared = kv.page_ids(2)
    kv.extend(2, 9)                          # a private third page
    assert kv.rollback(2, 8) == 1            # drops only the private page
    assert kv.page_ids(2) == shared
    assert kv.rollback(2, 3) == 1            # back into the shared blocks
    assert kv.ref(shared[1]) == 2            # seq 1 + the tree survive
    assert kv.page_ids(1) == shared
    assert set(tree.pages()) == set(shared)
    _assert_refs_consistent(kv, tree)
    kv.free(1)
    kv.free(2)
    tree.clear()
    assert kv.pages_in_use == 0


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_spec_rollback_refcounts_under_random_accept_reject(seed):
    """The speculative-decode KV contract (ISSUE 19): random verify
    cycles — extend by k+1, accept a random draft prefix, roll back the
    rest — interleaved with sharing, COW, frees, and evictions keep
    every refcount equal to its live references, and a drain leaves all
    of them zero."""
    rng = random.Random(1000 + seed)
    cfg = KVCacheConfig(n_layers=1, kv_heads=1, head_dim=4,
                        page_size=4, n_pages=rng.randrange(10, 16))
    kv = PagedKVCache(cfg)
    tree = PrefixCache(kv)
    next_sid = 1
    lanes: dict = {}  # sid -> token list (kept in sync with kv.length)
    for _ in range(400):
        op = rng.random()
        if op < 0.3:  # admit (with sharing when the tree matches)
            toks = [rng.randrange(4) for _ in range(rng.randrange(1, 13))]
            shared = tree.match(toks)
            need = cfg.pages_for(len(toks)) - len(shared)
            if need <= kv.free_pages:
                sid = next_sid
                next_sid += 1
                if shared:
                    kv.alloc_shared(sid, shared, len(toks))
                else:
                    kv.alloc(sid, len(toks))
                lanes[sid] = toks
        elif op < 0.45 and lanes:  # publish a prompt's full blocks
            sid = rng.choice(list(lanes))
            toks = lanes[sid]
            nfull = len(toks) // cfg.page_size
            if nfull:
                tree.insert(toks[:nfull * cfg.page_size],
                            kv.page_ids(sid)[:nfull])
        elif op < 0.75 and lanes:  # one verify tick: extend, accept, roll back
            sid = rng.choice(list(lanes))
            k = rng.randrange(1, 5)
            length = kv.length(sid)
            try:
                kv.extend(sid, length + k + 1)
            except OutOfPages:
                continue
            accepted = rng.randrange(0, k + 1)
            kv.rollback(sid, length + accepted + 1)
            lanes[sid] = lanes[sid] + [rng.randrange(4)
                                       for _ in range(accepted + 1)]
        elif op < 0.85 and lanes:  # retire / preempt
            sid = rng.choice(list(lanes))
            kv.free(sid)
            del lanes[sid]
        elif op < 0.92:  # evict one LRU cache leaf
            tree.evict()
        elif lanes:  # copy-on-write a random owned page
            sid = rng.choice(list(lanes))
            pages = kv.page_ids(sid)
            if pages:
                try:
                    kv.cow_page(sid, rng.randrange(len(pages)))
                except OutOfPages:
                    pass
        _assert_refs_consistent(kv, tree)
    for sid in list(lanes):
        kv.free(sid)
    tree.clear()
    assert kv.pages_in_use == 0
    assert not kv._ref
    assert len(tree) == 0


# ---------------------------------------------------------------------------
# the n-gram drafter (speculative decoding's proposer)
# ---------------------------------------------------------------------------


def test_ngram_drafter_observe_draft_recency_and_capacity():
    d = NgramDrafter(order=2, max_entries=4)
    assert len(d) == 0
    assert d.draft([1, 2, 3], 4) == []       # empty map proposes nothing
    assert d.observe([1, 2, 3, 4, 5]) == 3   # (1,2)->3 (2,3)->4 (3,4)->5
    assert len(d) == 3 and d.observed == 3
    assert d.draft([0, 1, 2], 3) == [3, 4, 5]
    assert d.draft([0, 1, 2], 2) == [3, 4]   # k caps the walk
    assert d.draft([9, 9], 3) == []          # unknown tail
    assert d.draft([1], 3) == []             # context shorter than order
    assert d.draft([0, 1, 2], 0) == []
    d.observe([2, 3, 9])                     # recency: last writer wins
    assert d.draft([1, 2], 2) == [3, 9]
    d.observe([7, 7, 7])                     # the 4th entry fills the cap
    assert len(d) == 4
    d.observe([8, 8, 8])                     # at capacity: new gram dropped
    assert len(d) == 4
    assert d.draft([8, 8], 1) == []
    d.observe([1, 2, 6])                     # ...but known grams update
    assert d.draft([1, 2], 1) == [6]
    assert d.proposed > 0
    with pytest.raises(ValueError, match="order"):
        NgramDrafter(order=0)
    with pytest.raises(ValueError, match="max_entries"):
        NgramDrafter(max_entries=0)


def test_token_streams_feed_drafter_warmup():
    """token_streams() replays every cached root-to-leaf prefix;
    warm_from_prefix absorbs them so a fresh replica drafts the hot
    preambles without re-reading any request."""
    cfg = KVCacheConfig(n_layers=1, kv_heads=1, head_dim=4,
                        page_size=4, n_pages=16)
    kv = PagedKVCache(cfg)
    tree = PrefixCache(kv)
    assert tree.token_streams() == []
    toks = [1, 2, 3, 4, 5, 6, 7, 8]
    kv.alloc(1, len(toks))
    tree.insert(toks, kv.page_ids(1))
    kv.alloc_shared(2, tree.match(toks[:4]), 8)
    branch = toks[:4] + [9, 9, 9, 9]
    tree.insert(branch, kv.page_ids(2))
    assert sorted(tree.token_streams()) == sorted([toks, branch])
    d = NgramDrafter(order=2)
    assert d.warm_from_prefix(tree) == 12    # 6 gram pairs per stream
    assert len(d) == 8                       # shared-root grams dedup
    assert d.draft([1, 2], 2) == [3, 4]
    assert d.draft([9, 9], 1) == [9]
    kv.free(1)
    kv.free(2)
    tree.clear()
    assert kv.pages_in_use == 0


def test_table_rows_matches_per_row_view():
    cfg = KVCacheConfig(n_layers=1, kv_heads=1, head_dim=4,
                        page_size=4, n_pages=16)
    kv = PagedKVCache(cfg)
    kv.alloc(1, 10)
    kv.alloc(2, 3)
    rows = kv.table_rows([2, 1], 4)
    assert rows.dtype == np.int32 and rows.shape == (2, 4)
    assert rows.tolist() == [kv.table_row(2, 4), kv.table_row(1, 4)]
    with pytest.raises(ValueError, match="max_pages"):
        kv.table_rows([1], 2)


def test_prefix_affinity_policy():
    replicas = [
        {"load": 5, "match": 0},
        {"load": 9, "match": 8},
        {"load": 1, "match": 0},
    ]
    pick, hit = prefix_affinity(
        replicas, lambda h: h["load"], lambda h: h["match"])
    assert pick is replicas[1] and hit  # longest prefix wins over load
    for r in replicas:
        r["match"] = 0
    pick, hit = prefix_affinity(
        replicas, lambda h: h["load"], lambda h: h["match"])
    assert pick is replicas[2] and not hit  # degenerates to least work
    assert prefix_affinity([], lambda h: 0, lambda h: 0) == (None, False)


# ---------------------------------------------------------------------------
# engine layer: sharing + chunking on the real hot path
# ---------------------------------------------------------------------------


def test_shared_prefix_storm_matches_oracle_and_reuses_pages(engine):
    """Requests sharing a page-aligned preamble: bitwise-oracle outputs,
    prefix hits counted, reused pages never re-prefilled (the
    prefill_tokens counter only covers suffixes), and a drain leaves
    every refcount at zero."""
    preamble = [(3 * i + 1) % 128 for i in range(8)]  # one full page
    # Arrivals spaced so each follower admits after the leader's prefill
    # published the preamble block (two chunks at prefill_chunk=6).
    reqs = [Request(f"p{i}", preamble + [i + 1, i + 2],
                    max_new_tokens=3, arrival_step=2 * i)
            for i in range(4)]
    observe.enable(True)
    try:
        hits0 = observe.counter("tdx.serve.prefix_hits").value
        reused0 = observe.counter("tdx.serve.prefix_tokens_reused").value
        out = engine.run(reqs)
        hits = observe.counter("tdx.serve.prefix_hits").value - hits0
        reused = (observe.counter("tdx.serve.prefix_tokens_reused").value
                  - reused0)
    finally:
        observe.enable(None)
    _check_oracle(engine, reqs, out)
    assert hits >= 3, hits          # every follower matched the preamble
    assert reused >= 3 * 8, reused
    engine.drain()
    assert engine.kv.pages_in_use == 0
    assert not engine.kv._ref


def test_sharing_off_arm_is_identical(engine):
    """prefix_cache=False must serve the same storm to the same tokens
    (the bench phases' control arm)."""
    eng_off = ServeEngine(
        "llama", LLAMA, engine.params,
        serve_cfg=ServeConfig(max_batch=2, page_size=8, n_pages=16,
                              max_pages_per_seq=3, prefill_buckets=(8, 16),
                              prefill_chunk=6, prefix_cache=False),
    )
    eng_off._programs.update(engine._programs)
    preamble = [(5 * i + 2) % 128 for i in range(8)]
    reqs = [Request(f"o{i}", preamble + [i + 3], max_new_tokens=3)
            for i in range(3)]
    out = eng_off.run(reqs)
    _check_oracle(eng_off, reqs, out)
    assert len(eng_off.prefix) == 0  # the off arm never populates the tree
    assert eng_off.kv.pages_in_use == 0


def test_fully_cached_aligned_prompt_cows_the_shared_tail(engine):
    """A page-aligned prompt that is FULLY cached recomputes exactly its
    last token — the one write aimed at a shared page; COW must give the
    grower a private copy (counted) and the outputs stay bitwise-equal
    to the oracle."""
    prompt = [(7 * i + 11) % 128 for i in range(16)]  # exactly two pages
    observe.enable(True)
    try:
        cow0 = observe.counter("tdx.serve.cow_copies").value
        out = engine.run([Request("cw0", prompt, max_new_tokens=2)])
        out2 = engine.run([Request("cw1", prompt, max_new_tokens=2)])
        cows = observe.counter("tdx.serve.cow_copies").value - cow0
    finally:
        observe.enable(None)
    assert cows >= 1, "the fully-cached admit must copy-on-write"
    want, _ = oracle_generate(engine.family, engine.cfg, engine.params,
                              prompt, 2)
    assert out["cw0"] == want and out2["cw1"] == want
    engine.drain()
    assert engine.kv.pages_in_use == 0


def test_chunked_prefill_interleaves_decode(engine):
    """While a long prompt prefills chunk-by-chunk, a short request
    admitted behind it starts DECODING before the long prefill finishes
    — the head-of-line-blocking fix chunking exists for."""
    long_req = Request("lng", [(11 * i + 5) % 128 for i in range(18)],
                       max_new_tokens=2)
    short = Request("sht", [9, 2, 9], max_new_tokens=4, arrival_step=1)
    first_tok_step: dict = {}
    prev = engine.on_token
    engine.on_token = lambda rid, tok: first_tok_step.setdefault(
        rid, engine._step_no)
    try:
        out = engine.run([long_req, short])
    finally:
        engine.on_token = prev
    _check_oracle(engine, [long_req, short], out)
    # 18 tokens at chunk 6 = 3 chunks = 3 engine ticks of prefill; the
    # short request's first token lands before the long one's.
    assert first_tok_step["sht"] < first_tok_step["lng"], first_tok_step
    engine.drain()
    assert engine.kv.pages_in_use == 0


def test_chaos_fault_between_chunks_requeues_without_leaks(engine):
    """serve@N=raise:chunk fires BETWEEN prefill chunks: the mid-prefill
    lane requeues (recompute), nothing leaks, shared prefixes stay
    intact, and outputs equal the fault-free oracle."""
    preamble = [(13 * i + 3) % 128 for i in range(8)]
    warm = Request("ck-warm", preamble + [1, 2], max_new_tokens=2)
    engine.run([warm])  # seed the tree with the shared preamble
    tree_pages = set(engine.prefix.pages())
    assert tree_pages
    reqs = [
        Request("ck-long", preamble + [(i * 3 + 1) % 128 for i in range(10)],
                max_new_tokens=3),
        Request("ck-short", [4, 4, 4], max_new_tokens=3),
    ]
    observe.enable(True)
    # _step_no is lifetime; target the tick where ck-long's SECOND chunk
    # would run (admission + first chunk land on the next tick).
    chaos.install(f"serve@{engine._step_no + 2}=raise:chunk")
    try:
        before = observe.counter("tdx.serve.preempted_requests").value
        out = engine.run(reqs)
        assert not chaos.active_plan().pending()
        assert (observe.counter("tdx.serve.preempted_requests").value
                > before)
    finally:
        chaos.clear()
        observe.enable(None)
    _check_oracle(engine, reqs, out)
    # The shared preamble survived the fault path un-corrupted and
    # un-freed.
    assert tree_pages <= set(engine.prefix.pages())
    engine.drain()
    assert engine.kv.pages_in_use == 0
    assert not engine.kv._ref
