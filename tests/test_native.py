"""Native graph engine: equivalence with the pure-Python reference walks."""

import subprocess
import sys

import pytest
import torch
import torch.nn as nn

from torchdistx_tpu import _native
from torchdistx_tpu._graph import CONTEXT_KEY, get_fake_context
from torchdistx_tpu.deferred_init import deferred_init, materialize_tensor

needs_native = pytest.mark.skipif(
    not _native.available(), reason="libtdxgraph.so not built (run `make native`)"
)


def _record_view_chain():
    def make():
        w = torch.empty(4, 4)
        w.fill_(1.0)
        v = w[0]
        v.add_(5.0)
        u = w.view(16)
        u.mul_(2.0)
        return w, v, u

    return deferred_init(make)


@needs_native
class TestNativeEquivalence:
    def test_call_stack_matches_python(self):
        w, v, u = _record_view_chain()
        ctx = get_fake_context(w, CONTEXT_KEY)
        node = ctx.node
        native_ids = [n.op_nr for n in node.build_call_stack()]
        # Force the Python implementation on the same graph.
        ng = node._ng
        try:
            node._ng = None
            python_ids = [n.op_nr for n in node.build_call_stack()]
        finally:
            node._ng = ng
        assert native_ids == python_ids

    def test_materialize_values(self):
        w, v, u = _record_view_chain()
        rw = materialize_tensor(w)
        assert rw[0, 0].item() == 12.0  # (1+5)*2
        assert rw[1, 1].item() == 2.0

    def test_node_destroy_on_gc(self):
        import gc

        g = _native.NativeGraph.current()
        before = len(g.py_nodes)
        t = deferred_init(lambda: torch.ones(3) * 2)
        del t
        gc.collect()
        after = len(g.py_nodes)
        assert after <= before + 1  # transient nodes were released

    def test_python_fallback_same_results(self):
        code = (
            "import torch, torch.nn as nn\n"
            "from torchdistx_tpu import _native\n"
            "from torchdistx_tpu.deferred_init import deferred_init, materialize_module\n"
            "assert not _native.available()\n"
            "torch.manual_seed(0)\n"
            "m = deferred_init(lambda: nn.Sequential(nn.Linear(8,16), nn.Linear(16,4)))\n"
            "materialize_module(m)\n"
            "print(float(torch.cat([p.flatten() for p in m.parameters()]).sum()))\n"
        )
        import os

        r = subprocess.run(
            [sys.executable, "-c", code],
            env={**os.environ, "TDX_NATIVE": "0"},
            capture_output=True,
            text=True,
        )
        assert r.returncode == 0, r.stderr[-500:]
        torch.manual_seed(0)
        m = deferred_init(lambda: nn.Sequential(nn.Linear(8, 16), nn.Linear(16, 4)))
        from torchdistx_tpu.deferred_init import materialize_module

        materialize_module(m)
        ours = float(torch.cat([p.flatten() for p in m.parameters()]).sum())
        assert abs(ours - float(r.stdout.strip())) < 1e-6


@needs_native
class TestMixedNativePython:
    def test_python_only_mutation_poisons_native_graph(self):
        # A node recorded under config.override(native=False) extends a
        # graph whose earlier nodes have native mirrors; the mirrors no
        # longer see the full topology and must be poisoned so walks fall
        # back to (correct) Python paths.
        import torchdistx_tpu.config as tdx_config
        from torchdistx_tpu.deferred_init import materialize_module  # noqa: F401

        def make():
            w = torch.zeros(4)
            return w

        w = deferred_init(make)
        zeros_node = get_fake_context(w, CONTEXT_KEY).node
        assert zeros_node._ng is not None
        with tdx_config.override(native=False):
            from torchdistx_tpu.deferred_init import enable_deferred_init

            enable_deferred_init(True)
            try:
                w.fill_(7.0)  # python-only node mutating the native graph
            finally:
                enable_deferred_init(False)
        assert zeros_node._ng.poisoned
        out = materialize_tensor(w)
        assert torch.equal(out, torch.full((4,), 7.0))
