"""End-to-end wiring tests for bench.main() with the phase-subprocess
boundary stubbed: the healthy-accelerator branch and the wedged-tunnel
fallback branch must BOTH end in a compact final stdout line that
survives the driver's ~2000-char tail capture (round 4 lost its
scoreboard record to a single giant line — BENCH_r04 parsed: null).

Hermetic: hardware-cache entries are written into the fixture's tmp
BCACHE_DIR, never read from the committed .bench_cache/.
"""

import importlib.util
import io
import contextlib
import json
import sys
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture()
def bench(monkeypatch, tmp_path):
    spec = importlib.util.spec_from_file_location("bench", REPO / "bench.py")
    mod = importlib.util.module_from_spec(spec)
    sys.modules["bench"] = mod
    spec.loader.exec_module(mod)
    monkeypatch.setattr(mod, "BCACHE_DIR", str(tmp_path / "bcache"))
    monkeypatch.setattr(mod, "CACHE_DIR", str(tmp_path / "jax"))
    monkeypatch.setattr(mod, "REPO", str(tmp_path))  # bench_full.json
    yield mod
    sys.modules.pop("bench", None)


def _write_hw(bench, name, result, age_s=3600):
    p = Path(bench.BCACHE_DIR)
    p.mkdir(parents=True, exist_ok=True)
    with open(p / f"{name}.json", "w") as f:
        json.dump({"ts": time.time() - age_s, "platform": "tpu",
                   "result": result}, f)


_HOST_PHASES = {
    "t5_sharded": {"t": 5.1, "rss_mb": 2287.0, "n_params": 75191808,
                   "n_sharded": 129, "warm": True, "_backend": "cpu"},
    "mixtral_sharded": {"t": 4.2, "rss_mb": 1731.0, "n_params": 29763856,
                        "n_sharded": 114, "warm": True, "_backend": "cpu"},
    "llama70b_lower": {"record_s": 0.65, "lower_s": 0.45,
                       "export_tpu_s": 0.43, "export_mb": 0.3,
                       "n_params": 70553706496, "n_outputs": 724,
                       "rss_mb": 1219.5},
    "t5_11b_lower": {"record_s": 0.46, "lower_s": 0.45, "export_tpu_s": 0.44,
                     "export_mb": 0.22, "n_params": 11307321344,
                     "n_outputs": 509, "rss_mb": 1216.0},
    "mixtral_8x7b_lower": {"record_s": 0.79, "lower_s": 1.25,
                           "export_tpu_s": 1.13, "export_mb": 0.06,
                           "n_params": 46702792736, "n_outputs": 14,
                           "rss_mb": 428.6},
    "materialize_pipeline": {
        "n_layers": 128, "n_cpus": 8, "repeats": 3, "cold_off_s": 36.6,
        "cold_auto_s": 26.0, "warm_auto_s": 4.0, "n_programs": 21,
        "workers": 4, "overlap": 3.8, "bitwise_equal": True,
        "pipeline_speedup": 1.408, "backend": "cpu", "_backend": "cpu"},
    "materialize_bandwidth": {
        "n_slabs": 32, "repeats": 3, "warm_default_s": 0.104,
        "warm_bf16_s": 0.122, "warm_bf16_no_overlap_s": 0.139,
        "warm_monolith_s": 0.104,
        "bitwise_equal": True, "n_bytes_mb": 268.7,
        "materialize_gbps": 2.584, "overlap_speedup": 0.933,
        "link_bandwidth_gbps": 3.137, "link_probe_mb": 32,
        "materialize_link_utilization": 0.82345, "n_programs": 8,
        "transfer_overlap": 0.61, "bytes_donated": 8398848,
        "device_put_batches": 0, "warm_execute_s": 0.077,
        "backend": "cpu", "_backend": "cpu"},
    "pp_bubble": {"schedule_analysis": {"pp4_v2_m8": {"interleaved_ticks": 26}}},
    "reshard": {
        "n_leaves": 16, "repeats": 2, "reshard_s": 0.41,
        "reshard_bytes_moved": 134217728, "reshard_bytes_total": 134217904,
        "reshard_chunks": 64, "reshard_peak_host_bytes": 16777216,
        "reshard_gbps": 0.327, "backend": "cpu", "_backend": "cpu"},
    "serving": {
        "bring_up_cold_s": 4.1, "ttft_cold_s": 4.13,
        "bring_up_warm_s": 0.77, "ttft_warm_s": 0.77,
        "ttft_warm_speedup": 5.34, "decode_tokens_per_s": 1360.0,
        "warm_local_compiles": 0, "oracle_equal": True,
        "backend": "cpu", "_backend": "cpu"},
    "serving_fleet": {
        "bring_up_cold_s": 4.3, "fleet_scale_up_warm_s": 0.81,
        "fleet_scaleup_warm_speedup": 5.26,
        "fleet_tokens_per_s": {"1": 944.6, "2": 1111.0, "4": 1027.1},
        "fleet_scaling_efficiency_2r": 1.176, "chaos_requeued": 4,
        "warm_local_compiles": 0, "oracle_equal": True,
        "host_cpu_count": 1, "backend": "cpu", "_backend": "cpu"},
    "serving_prefix": {
        "storm_requests": 48, "prefix_hits": 38,
        "prefix_tokens_reused": 1824, "prefix_cow": 2,
        "prefill_chunks": 150,
        "prefix_off_tokens_per_s": 357.2, "prefix_on_tokens_per_s": 656.9,
        "prefix_tokens_per_s_improvement": 1.839,
        "prefix_off_p95_ttft_s": 0.0132, "prefix_on_p95_ttft_s": 0.0071,
        "prefix_p95_ttft_improvement": 1.848,
        "chunked_short_ttft_coarse_s": 0.0119,
        "chunked_short_ttft_fine_s": 0.0091,
        "prefix_chunked_short_ttft_improvement": 1.31, "oracle_equal": True,
        "host_cpu_count": 1, "backend": "cpu", "_backend": "cpu"},
    "serving_spec": {
        "storm_requests": 40, "spec_off_tokens_per_s": 544.0,
        "spec_on_tokens_per_s": 1809.0,
        "spec_tokens_per_s_improvement": 3.322,
        "spec_drafted": 350, "spec_accepted": 230,
        "spec_verify_ticks": 39, "spec_accept_rate": 0.657,
        "spec_accepted_per_verify": 5.846, "oracle_equal": True,
        "host_cpu_count": 1, "backend": "cpu", "_backend": "cpu"},
    "serving_ledger": {
        "storm_requests": 48, "ledger_off_tokens_per_s": 661.0,
        "ledger_on_tokens_per_s": 657.0, "ledger_overhead_ratio": 0.994,
        "ledger_stage_queue_p50_s": 0.0021, "ledger_stage_queue_p99_s": 0.011,
        "ledger_stage_queue_share": 0.31,
        "ledger_stage_prefill_p50_s": 0.0009,
        "ledger_stage_prefill_p99_s": 0.0041,
        "ledger_stage_prefill_share": 0.12,
        "ledger_stage_decode_p50_s": 0.0034,
        "ledger_stage_decode_p99_s": 0.0089,
        "ledger_stage_decode_share": 0.55,
        "ledger_stage_guardrail_p50_s": 0.0,
        "ledger_stage_guardrail_p99_s": 0.0,
        "ledger_stage_guardrail_share": 0.02,
        "ledger_p99_blame_queue": 0.44, "ledger_p99_blame_prefill": 0.08,
        "ledger_p99_blame_decode": 0.46, "ledger_p99_blame_guardrail": 0.02,
        "ledger_e2e_p99_s": 0.021, "oracle_equal": True,
        "host_cpu_count": 1, "backend": "cpu", "_backend": "cpu"},
    "serving_rollover": {
        "storm_requests": 24, "steady_tokens_per_s": 612.0,
        "rollover_tokens_per_s": 588.0,
        "rollover_tokens_per_s_ratio": 0.961,
        "steady_p95_ttft_s": 0.031, "rollover_p95_ttft_s": 0.042,
        "rollover_roll_s": 9.4, "rollover_blue_drains": 2,
        "warm_local_compiles": 0, "oracle_equal": True,
        "host_cpu_count": 1, "backend": "cpu", "_backend": "cpu"},
    "guardrails": {
        "storm_requests": 48, "bring_up_cold_s": 4.2,
        "guardrails_breaker_trips": 1, "guardrails_hedged": 0,
        "guardrails_shed_low": 20, "warm_local_compiles": 0,
        "guardrails_off_p95_ttft_s": 0.247,
        "guardrails_on_p95_ttft_s": 0.134,
        "guardrails_p95_ttft_improvement": 1.848, "oracle_equal": True,
        "host_cpu_count": 1, "backend": "cpu", "_backend": "cpu"},
    "schedule_measured": {"schedule_measured": {
        "gpipe_step_ms": 1769.0, "flat_1f1b_step_ms": 2509.0,
        "interleaved_step_ms": 2078.0, "interleaved_vs_flat_measured": 1.208,
        "platform_note": "8-device virtual CPU mesh"}, "_backend": "cpu"},
}


def _run_main(bench, payloads):
    def fake_run_phase(name, timeout=600.0, cache_fallback=False):
        return dict(payloads[name])

    bench._run_phase = fake_run_phase
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        bench.main()
    stdout = buf.getvalue()
    lines = stdout.strip().splitlines()
    # Simulate the driver: only the last ~2000 chars survive.
    headline = json.loads(stdout[-2000:].strip().splitlines()[-1])
    return json.loads(lines[0]), headline, lines


def test_healthy_branch_headline_and_detail(bench):
    payloads = {
        **_HOST_PHASES,
        "gpt2_baseline": {"t": 33.1, "rss_mb": 2500.0, "_backend": "tpu"},
        "gpt2_ours": {"t": 2.7, "rss_mb": 1800.0, "warm": True,
                      "materialize_gbps": 0.19, "_backend": "tpu"},
        "llama_ours": {"t": 2.6, "rss_mb": 4100.0, "n_params": 1480000000,
                       "materialize_gbps": 2.3, "_backend": "tpu"},
        "llama_baseline": {"t": 266.0, "rss_mb": 9000.0, "_backend": "tpu"},
        "llama_big_ours": {"t": 14.2, "rss_mb": 2100.0, "warm": True,
                           "n_params": 6738415616,
                           "param_dtype": "bfloat16", "record_s": 1.1,
                           "materialize_s": 12.0, "touch_s": 1.1,
                           "materialize_gbps": 0.95, "_backend": "tpu"},
        "flash": {"flash_ms": 0.99, "ref_ms": 4.6, "flash_tflops": 34.9,
                  "ref_tflops": 7.6, "speedup": 4.64,
                  "device_kind": "TPU v5 lite", "blocks": [1024, 1024],
                  "mfu": 0.177, "ref_mfu": 0.038, "_backend": "tpu"},
        "flash_bwd": {"flash_ms": 3.58, "ref_ms": 13.6, "speedup": 3.79,
                      "device_kind": "TPU v5 lite", "blocks": [1024, 1024],
                      "mfu": 0.171, "ref_mfu": 0.045, "_backend": "tpu"},
        "flash_bias": {"flash_ms": 1.88, "ref_ms": 5.04, "speedup": 2.68,
                       "device_kind": "TPU v5 lite", "blocks": [512, 1024],
                       "mfu": 0.186, "ref_mfu": 0.069, "_backend": "tpu"},
        "train_mfu": {"step_ms": 185.0, "tokens_per_s": 44300, "mfu": 0.31,
                      "device_kind": "TPU v5 lite", "n_params": 124000000,
                      "_backend": "tpu"},
    }
    bench._preflight_platform = lambda: ""
    full, headline, lines = _run_main(bench, payloads)
    assert len(lines) == 2
    assert len(lines[-1]) <= bench._HEADLINE_BUDGET
    assert headline["vs_baseline"] == round(33.1 / 2.7, 3)
    assert headline["train_mfu"] == 0.31
    assert headline["flash_mfu"] == 0.177
    assert headline["llama_big_n_params"] == 6738415616
    assert headline["llama_big_materialize_gbps"] == 0.95
    assert headline["t5_11b_n_params"] == 11307321344
    assert headline["mixtral_8x7b_rss_mb"] == 428.6
    assert full["llama_1p9b_vs_baseline"] == round(266.0 / 2.6, 3)
    assert full["llama_big_param_dtype"] == "bfloat16"
    assert headline["pipeline_speedup"] == 1.408
    assert headline["reshard_gbps"] == 0.327
    assert headline["fleet_scaleup_warm_speedup"] == 5.26
    assert headline["fleet_scaling_efficiency_2r"] == 1.176
    assert full["serving_fleet"]["chaos_requeued"] == 4
    assert headline["guardrails_p95_ttft_improvement"] == 1.848
    assert full["guardrails"]["guardrails_breaker_trips"] == 1
    assert headline["prefix_tokens_per_s_improvement"] == 1.839
    assert headline["prefix_p95_ttft_improvement"] == 1.848
    assert full["serving_prefix"]["prefix_hits"] == 38
    assert headline["ledger_overhead_ratio"] == 0.994
    assert full["serving_ledger"]["ledger_p99_blame_queue"] == 0.44
    assert headline["rollover_tokens_per_s_ratio"] == 0.961
    assert full["serving_rollover"]["rollover_blue_drains"] == 2
    assert full["reshard_bytes_moved"] == 134217728
    assert full["materialize_pipeline"]["bitwise_equal"] is True
    assert full["schedule_measured"]["interleaved_vs_flat_measured"] == 1.208
    assert json.load(open(Path(bench.REPO) / "bench_full.json")) == full


def test_fallback_expired_cache_not_promoted(bench, monkeypatch):
    # A cached hardware headline older than TDX_BENCH_MAX_STALE_S must be
    # marked expired and kept OUT of value/vs_baseline (round 5 published
    # a 118k-second-old number with no bound).
    monkeypatch.delenv("TDX_BENCH_MAX_STALE_S", raising=False)
    _write_hw(bench, "gpt2_ours", {"t": 2.7, "rss_mb": 1800.0},
              age_s=118_000)
    _write_hw(bench, "gpt2_baseline", {"t": 33.1, "rss_mb": 2500.0},
              age_s=118_000)
    payloads = {
        **_HOST_PHASES,
        "gpt2_baseline": {"t": 400.0, "rss_mb": 2500.0, "_backend": "cpu"},
        "gpt2_ours": {"t": 60.0, "rss_mb": 1800.0, "warm": False,
                      "materialize_gbps": 0.008, "_backend": "cpu"},
    }
    bench._preflight_platform = (
        lambda: "cpu(fallback: accelerator backend unreachable)")
    full, headline, lines = _run_main(bench, payloads)
    assert headline["headline_from_cache"] is False
    assert 117_000 <= full["headline_cache_expired_s"] <= 119_000
    assert full["headline_cache_max_stale_s"] == 86400
    # The headline pair stays the fresh (CPU-labeled) measurement.
    assert headline["value"] == 60.0
    assert headline["vs_baseline"] == round(400.0 / 60.0, 3)
    assert "headline_age_s" not in full

    # Raising the bound re-admits the same cache entries.
    monkeypatch.setenv("TDX_BENCH_MAX_STALE_S", "200000")
    full2, headline2, _ = _run_main(bench, payloads)
    assert headline2["headline_from_cache"] is True
    assert headline2["vs_baseline"] == round(33.1 / 2.7, 3)
    assert "headline_cache_expired_s" not in full2


def test_fallback_branch_promotes_cached_hardware(bench):
    # Committed-hardware-cache stand-ins in the hermetic tmp dir.
    _write_hw(bench, "gpt2_ours", {"t": 2.7, "rss_mb": 1800.0,
                                   "materialize_gbps": 0.19})
    _write_hw(bench, "gpt2_baseline", {"t": 33.1, "rss_mb": 2500.0})
    _write_hw(bench, "flash", {"flash_ms": 0.985, "speedup": 4.59,
                               "mfu": 0.177})
    payloads = {
        **_HOST_PHASES,
        "gpt2_baseline": {"t": 400.0, "rss_mb": 2500.0, "_backend": "cpu"},
        "gpt2_ours": {"t": 60.0, "rss_mb": 1800.0, "warm": False,
                      "materialize_gbps": 0.008, "_backend": "cpu"},
    }
    bench._preflight_platform = (
        lambda: "cpu(fallback: accelerator backend unreachable)")
    full, headline, lines = _run_main(bench, payloads)
    assert headline["headline_from_cache"] is True
    assert headline["vs_baseline"] == round(33.1 / 2.7, 3)
    assert 3500 <= headline["headline_age_s"] <= 3700
    assert full["cpu_fresh_vs_baseline"] == round(400.0 / 60.0, 3)
    assert full["flash_skipped"] == "accelerator unavailable"
    assert full["flash_ms"] == 0.985 and full["flash_stale_s"] > 0
    # No cached train_mfu / llama_big entries: skipped markers, nothing
    # fabricated.
    assert full["train_mfu_skipped"] == "accelerator unavailable"
    assert "train_mfu" not in full
    assert full["llama_big_skipped"] == "accelerator unavailable"
    assert "llama_big_ours_s" not in full
