"""FSDP integration tests.

torch FSDP's deferred-init support imports ``torchdistx`` at
``torch.distributed.fsdp`` import time, so the shim tests run in
subprocesses where the import order can be controlled. The process group
is single-rank gloo — the CPU stand-in for a pod, same spirit as the
virtual CPU mesh for the jax tests.
"""

import os
import subprocess
import sys

import pytest
import torch

from torchdistx_tpu.deferred_init import deferred_init
from torchdistx_tpu.fake import is_fake
from torchdistx_tpu.fsdp import make_param_init_fn, make_xla_param_init_fn


def _run(script: str) -> subprocess.CompletedProcess:
    env = dict(os.environ)
    env.update(
        MASTER_ADDR="127.0.0.1",
        MASTER_PORT="29517",
        PYTHONPATH=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    return subprocess.run(
        [sys.executable, "-c", script], env=env, capture_output=True, text=True,
        timeout=240,
    )


def test_param_init_fn_materializes_in_place():
    m = deferred_init(torch.nn.Linear, 8, 4)
    assert is_fake(m.weight)
    make_param_init_fn()(m)
    assert not is_fake(m.weight)
    out = m(torch.randn(2, 8))
    assert torch.isfinite(out).all()


def test_xla_param_init_fn_requires_torch_xla():
    pytest.importorskip("torch", reason="torch required")
    try:
        import torch_xla  # noqa: F401

        pytest.skip("torch_xla installed; error path not applicable")
    except ImportError:
        pass
    with pytest.raises(RuntimeError, match="requires torch_xla"):
        make_xla_param_init_fn()


def test_xla_param_init_fn_executes_with_stub(monkeypatch):
    # torch_xla is not installable in this image (VERDICT r2 weak #8:
    # the variant had never executed anywhere); a stub torch_xla proves
    # the variant's OWN logic — xm.xla_device() resolution and the
    # device-rewriting ReplayTarget — end to end, with only the real
    # torch_xla device swapped for cpu.
    import sys
    import types

    xm = types.ModuleType("torch_xla.core.xla_model")
    xm.xla_device = lambda: torch.device("cpu")
    core = types.ModuleType("torch_xla.core")
    core.xla_model = xm
    txla = types.ModuleType("torch_xla")
    txla.core = core
    monkeypatch.setitem(sys.modules, "torch_xla", txla)
    monkeypatch.setitem(sys.modules, "torch_xla.core", core)
    monkeypatch.setitem(sys.modules, "torch_xla.core.xla_model", xm)

    torch.manual_seed(0)
    m = deferred_init(torch.nn.Linear, 8, 4)
    make_xla_param_init_fn()(m)  # device from xm.xla_device()
    assert not is_fake(m.weight)
    assert m.weight.device.type == "cpu"
    torch.manual_seed(0)
    ref = torch.nn.Linear(8, 4)
    assert torch.equal(m.weight, ref.weight)
    assert torch.isfinite(m(torch.randn(2, 8))).all()

    # Explicit device= override skips xla_device() but keeps the import.
    m2 = deferred_init(torch.nn.Linear, 4, 2)
    make_xla_param_init_fn(device="cpu")(m2)
    assert not is_fake(m2.weight)


def test_shim_provides_torchdistx_surface():
    r = _run(
        """
import torch
from torchdistx_tpu.fsdp import install_torchdistx_shim
install_torchdistx_shim()
from torchdistx import deferred_init, fake
with fake.fake_mode():
    t = torch.ones(10)
assert fake.is_fake(t)
m = deferred_init.deferred_init(torch.nn.Linear, 4, 2)
deferred_init.materialize_module(m)
assert not fake.is_fake(m.weight)
print("SHIM-OK")
"""
    )
    assert "SHIM-OK" in r.stdout, r.stderr


def _accelerator_hooks_missing() -> bool:
    # fake.py renames privateuse1 to "tpu" and then registers python
    # dummy accelerator hooks via torch._C._acc — an API this torch
    # build (2.9) does not ship, so torch.accelerator consumers raise
    # "register PrivateUse1HooksInterface first" until a C++ extension
    # provides the hooks.  The import above already ran the rename.
    try:
        torch._C._get_accelerator()
        return False
    except RuntimeError:
        return True


# strict: if a torch upgrade restores the hook API these must pass again.
_needs_acc_hooks = pytest.mark.xfail(
    _accelerator_hooks_missing(), strict=True,
    reason="this torch build cannot register privateuse1 accelerator "
           "hooks from python (fake.py warns at import)",
)


@_needs_acc_hooks
def test_accelerator_api_survives_import():
    # Renaming privateuse1 to "tpu" must not break torch.accelerator
    # consumers (torch FSDP queries _get_accelerator during init).
    r = _run(
        """
import torchdistx_tpu.fake
import torch
torch._C._get_accelerator()
print("ACC-OK")
"""
    )
    assert "ACC-OK" in r.stdout, r.stderr


# Note: forward/backward THROUGH torch FSDP cannot run here — this torch
# build raises "FSDP does not support CPU only execution" at _lazy_init on
# any model, ours or not. The integration surface (FSDP detecting fakes
# and materializing them during wrapping) is exactly what these assert.


@_needs_acc_hooks  # FSDP wrap queries torch.accelerator during init
def test_fsdp_with_param_init_fn():
    r = _run(
        """
import torch, torch.distributed as dist
from torchdistx_tpu.fsdp import install_torchdistx_shim, param_init_fn
install_torchdistx_shim()  # before FSDP import: enables fake detection
from torch.distributed.fsdp import FullyShardedDataParallel as FSDP
from torchdistx_tpu.deferred_init import deferred_init, materialize_module
from torchdistx_tpu.fake import is_fake

dist.init_process_group("gloo", rank=0, world_size=1)
build = lambda: torch.nn.Sequential(torch.nn.Linear(16, 16), torch.nn.Linear(16, 4))
model = deferred_init(build)
assert is_fake(model[0].weight)
torch.manual_seed(11)
wrapped = FSDP(model, param_init_fn=param_init_fn)
inner = wrapped.module
assert all(not is_fake(p) for p in inner.parameters())
# Values match a plain materialization under the same seed.
ref = deferred_init(build)
torch.manual_seed(11)
materialize_module(ref)
assert torch.equal(inner[0].weight.detach(), ref[0].weight.detach())
dist.destroy_process_group()
print("FSDP-OK")
"""
    )
    assert "FSDP-OK" in r.stdout, r.stderr


@_needs_acc_hooks  # FSDP wrap queries torch.accelerator during init
def test_fsdp_builtin_torchdistx_path():
    # No param_init_fn: FSDP's own torchdistX branch calls our
    # materialize_module(check_fn=...) — the strongest call-compat check.
    r = _run(
        """
import torch, torch.distributed as dist
from torchdistx_tpu.fsdp import install_torchdistx_shim
install_torchdistx_shim()
from torch.distributed.fsdp import FullyShardedDataParallel as FSDP
from torchdistx_tpu.deferred_init import deferred_init
from torchdistx_tpu.fake import is_fake

dist.init_process_group("gloo", rank=0, world_size=1)
model = deferred_init(
    lambda: torch.nn.Sequential(torch.nn.Linear(16, 16), torch.nn.Linear(16, 4))
)
wrapped = FSDP(model)
assert all(not is_fake(p) for p in wrapped.module.parameters())
assert all(torch.isfinite(p).all() for p in wrapped.module.parameters())
dist.destroy_process_group()
print("BUILTIN-OK")
"""
    )
    assert "BUILTIN-OK" in r.stdout, r.stderr


def test_xla_variant_not_exported():
    # VERDICT r4 missing #1: never executed on a real xla device, so it
    # stays off the advertised surface until it can be.
    from torchdistx_tpu import fsdp

    assert "make_xla_param_init_fn" not in fsdp.__all__


def test_xla_param_init_fn_on_real_xla_device():
    """The real-device arm of VERDICT r4 missing #1 — runs only where a
    genuine torch_xla is installed (nightly torch_xla_probe job with
    PJRT_DEVICE=CPU); everywhere else it skips.  When this passes in a
    real torch_xla environment, make_xla_param_init_fn can be promoted
    back into fsdp.__all__."""
    pytest.importorskip("torch_xla", reason="real torch_xla required")
    import torch_xla.core.xla_model as xm

    dev = xm.xla_device()
    torch.manual_seed(0)
    m = deferred_init(torch.nn.Linear, 8, 4)
    make_xla_param_init_fn()(m)
    assert not is_fake(m.weight)
    assert m.weight.device.type == "xla"
    out = m(torch.randn(2, 8).to(dev))
    assert torch.isfinite(out.cpu()).all()
