"""Autotuner tests (interpret mode; numbers are meaningless on CPU but
the search/caching contract — including the real cache path resolution
through the config layer — is fully exercised)."""

import json
import os

import jax
import pytest

from torchdistx_tpu import config
from torchdistx_tpu.ops import autotune, tune_flash_blocks


@pytest.fixture
def cache_dir(tmp_path):
    # Route through the REAL _cache_path / config layer (a lambda
    # monkeypatch of _cache_path once hid an ImportError inside it).
    with config.override(cache_dir=str(tmp_path)):
        yield tmp_path


def test_returns_candidate_and_caches(cache_dir, monkeypatch):
    cands = ((16, 16), (32, 16))
    blocks = tune_flash_blocks(
        batch=1, seq_len=32, heads=2, head_dim=16, candidates=cands,
    )
    assert blocks in cands
    path = autotune._cache_path()
    assert os.path.dirname(path) == str(cache_dir)
    data = json.load(open(path))
    key = next(iter(data))
    assert jax.devices()[0].device_kind in key
    assert "bfloat16" in key  # dtype is part of the key
    assert "interpret=" in key  # interpreter winners never serve real chips
    # Second call hits the cache: measuring again would be a bug.
    monkeypatch.setattr(
        autotune, "_measure",
        lambda *a, **k: pytest.fail("re-measured despite a valid cache hit"),
    )
    again = tune_flash_blocks(
        batch=1, seq_len=32, heads=2, head_dim=16, candidates=cands,
    )
    assert again == blocks


def test_cached_winner_outside_candidates_remeasures(cache_dir):
    # A cached winner must not be served to a call whose candidate set
    # excludes it (e.g. a memory-constrained caller).
    tune_flash_blocks(
        batch=1, seq_len=32, heads=2, head_dim=16, candidates=((32, 32),),
    )
    blocks = tune_flash_blocks(
        batch=1, seq_len=32, heads=2, head_dim=16, candidates=((16, 16),),
    )
    assert blocks == (16, 16)


def test_oversized_candidates_clamp(cache_dir):
    # seq_len below every candidate: clamp like flash_attention does
    # instead of refusing to tune short contexts.
    blocks = tune_flash_blocks(
        batch=1, seq_len=8, heads=2, head_dim=16,
        candidates=((64, 64), (128, 64)), use_cache=False,
    )
    assert blocks == (8, 8)


def test_empty_candidates_raise(cache_dir):
    with pytest.raises(ValueError, match="candidate list is empty"):
        tune_flash_blocks(
            batch=1, seq_len=8, heads=2, head_dim=16,
            candidates=(), use_cache=False,
        )
