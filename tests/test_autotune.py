"""Autotuner tests (interpret mode; numbers are meaningless on CPU but
the search/caching contract — including the real cache path resolution
through the config layer — is fully exercised)."""

import json
import os

import jax
import pytest

from torchdistx_tpu import config
from torchdistx_tpu.ops import autotune, tune_flash_blocks


@pytest.fixture
def cache_dir(tmp_path):
    # Route through the REAL _cache_path / config layer (a lambda
    # monkeypatch of _cache_path once hid an ImportError inside it).
    with config.override(cache_dir=str(tmp_path)):
        yield tmp_path


def test_returns_candidate_and_caches(cache_dir):
    cands = ((16, 16), (32, 16))
    blocks = tune_flash_blocks(
        batch=1, seq_len=32, heads=2, head_dim=16, candidates=cands,
    )
    assert blocks in cands
    path = autotune._cache_path()
    assert os.path.dirname(path) == str(cache_dir)
    data = json.load(open(path))
    key = next(iter(data))
    assert jax.devices()[0].device_kind in key
    assert "float32" in key or "bfloat16" in key  # dtype is part of the key
    # Second call hits the cache: poison the candidate list to prove the
    # measurement loop never runs.
    again = tune_flash_blocks(
        batch=1, seq_len=32, heads=2, head_dim=16, candidates=(),
    )
    assert again == blocks


def test_no_fitting_candidate_raises(cache_dir):
    with pytest.raises(ValueError, match="no candidate fits"):
        tune_flash_blocks(
            batch=1, seq_len=8, heads=2, head_dim=16,
            candidates=((64, 64),), use_cache=False,
        )
