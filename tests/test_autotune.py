"""Autotuner tests (interpret mode; numbers are meaningless on CPU but
the search/caching contract — including the real cache path resolution
through the config layer — is fully exercised)."""

import json
import os

import jax
import pytest

from torchdistx_tpu import config
from torchdistx_tpu.ops import autotune, tune_flash_blocks


@pytest.fixture
def cache_dir(tmp_path):
    # Route through the REAL _cache_path / config layer (a lambda
    # monkeypatch of _cache_path once hid an ImportError inside it).
    with config.override(cache_dir=str(tmp_path)):
        yield tmp_path


def test_returns_candidate_and_caches(cache_dir, monkeypatch):
    cands = ((16, 16), (32, 16))
    blocks = tune_flash_blocks(
        batch=1, seq_len=32, heads=2, head_dim=16, candidates=cands,
    )
    assert blocks in cands
    path = autotune._cache_path()
    assert os.path.dirname(path) == str(cache_dir)
    data = json.load(open(path))
    key = next(iter(data))
    assert jax.devices()[0].device_kind in key
    assert "bfloat16" in key  # dtype is part of the key
    assert "interpret=" in key  # interpreter winners never serve real chips
    # Second call hits the cache: measuring again would be a bug.
    monkeypatch.setattr(
        autotune, "_measure",
        lambda *a, **k: pytest.fail("re-measured despite a valid cache hit"),
    )
    again = tune_flash_blocks(
        batch=1, seq_len=32, heads=2, head_dim=16, candidates=cands,
    )
    assert again == blocks


def test_cached_winner_outside_candidates_remeasures(cache_dir):
    # A cached winner must not be served to a call whose candidate set
    # excludes it (e.g. a memory-constrained caller).
    tune_flash_blocks(
        batch=1, seq_len=32, heads=2, head_dim=16, candidates=((32, 32),),
    )
    blocks = tune_flash_blocks(
        batch=1, seq_len=32, heads=2, head_dim=16, candidates=((16, 16),),
    )
    assert blocks == (16, 16)


def test_oversized_candidates_clamp(cache_dir):
    # seq_len below every candidate: clamp like flash_attention does
    # instead of refusing to tune short contexts.
    blocks = tune_flash_blocks(
        batch=1, seq_len=8, heads=2, head_dim=16,
        candidates=((64, 64), (128, 64)), use_cache=False,
    )
    assert blocks == (8, 8)


def test_empty_candidates_raise(cache_dir):
    with pytest.raises(ValueError, match="candidate list is empty"):
        tune_flash_blocks(
            batch=1, seq_len=8, heads=2, head_dim=16,
            candidates=(), use_cache=False,
        )


def test_compile_failure_raises_block_config_error():
    # A candidate whose tiles overrun scoped vmem dies in Mosaic
    # compilation (v5e: [1024,1024] + f32 bias tile, round-4 capture).
    # _measure flags it as a per-config failure (BlockConfigError) so
    # the tuner can let survivors compete — and still detect the
    # all-configs-failed systemic case.
    import jax.numpy as jnp

    def boom(q, k, v):
        raise RuntimeError("RESOURCE_EXHAUSTED: scoped vmem")

    q = k = v = jnp.zeros((1, 8, 1, 8), jnp.float32)
    with pytest.raises(autotune.BlockConfigError):
        autotune._measure(boom, q, k, v)


def test_oom_candidate_loses_to_fitting_one(cache_dir, monkeypatch):
    import importlib

    # The package re-exports the FUNCTION under the same name; fetch
    # the module itself, which is what the tuner imports from.
    fa_mod = importlib.import_module("torchdistx_tpu.ops.flash_attention")
    real = fa_mod.flash_attention

    def gated(q, k, v, *a, block_q=None, block_k=None, **kw):
        if block_q == 32:
            raise RuntimeError("RESOURCE_EXHAUSTED: scoped vmem")
        return real(q, k, v, *a, block_q=block_q, block_k=block_k, **kw)

    monkeypatch.setattr(fa_mod, "flash_attention", gated)
    blocks = tune_flash_blocks(
        batch=1, seq_len=32, heads=2, head_dim=16,
        candidates=((32, 16), (16, 16)), use_cache=False,
    )
    assert blocks == (16, 16)


def test_all_candidates_noise_returns_smallest(cache_dir, monkeypatch):
    # Everything measured as noise (host hiccups): hand back the
    # smallest tile — the most likely to fit — and do not cache it.
    monkeypatch.setattr(autotune, "_measure", lambda *a, **k: float("inf"))
    blocks = tune_flash_blocks(
        batch=1, seq_len=64, heads=2, head_dim=16,
        candidates=((64, 64), (16, 16), (64, 16)), use_cache=True,
    )
    assert blocks == (16, 16)
    assert autotune._read_cache("anything") is None and not os.path.exists(
        autotune._cache_path()
    )


def test_all_candidates_compile_failing_raises(cache_dir, monkeypatch):
    # EVERY config crashing the compiler is systemic (broken helper
    # env, a Mosaic bug) — tuning must not "succeed" with the smallest
    # tile as if it had measured something.
    def boom(*a, **k):
        raise autotune.BlockConfigError("tpu_compile_helper subprocess exit code 1")

    monkeypatch.setattr(autotune, "_measure", boom)
    with pytest.raises(autotune.BlockConfigError):
        tune_flash_blocks(
            batch=1, seq_len=64, heads=2, head_dim=16,
            candidates=((64, 64), (16, 16)), use_cache=False,
        )


def test_non_vmem_compile_error_propagates():
    # Only memory-shaped failures measure as inf; a broken program must
    # raise so the caller learns the kernel cannot run at this shape.
    import jax.numpy as jnp

    def boom(q, k, v):
        raise ValueError("head_dim violates Mosaic tiling rules")

    q = k = v = jnp.zeros((1, 8, 1, 8), jnp.float32)
    with pytest.raises(ValueError, match="tiling rules"):
        autotune._measure(boom, q, k, v)


def test_hbm_oom_propagates():
    # HBM OOM carries RESOURCE_EXHAUSTED too, but no block size fixes
    # it — tuning must fail loudly, not "win" with the smallest tile.
    import jax.numpy as jnp

    def boom(q, k, v):
        raise RuntimeError(
            "RESOURCE_EXHAUSTED: Out of memory allocating 12884901888 "
            "bytes in hbm"
        )

    q = k = v = jnp.zeros((1, 8, 1, 8), jnp.float32)
    with pytest.raises(RuntimeError, match="in hbm"):
        autotune._measure(boom, q, k, v)


def test_vmem_trigger_reports_matched_substring():
    assert autotune._vmem_trigger(
        RuntimeError("Scoped allocation with size 9 exceeded scoped vmem limit")
    ) == "vmem"
    assert autotune._vmem_trigger(
        RuntimeError("Scoped allocation with size 9 exceeded the limit")
    ) == "Scoped allocation"
    assert autotune._vmem_trigger(
        RuntimeError("HTTP 500: tpu_compile_helper subprocess exit code 1")
    ) == "tpu_compile_helper subprocess exit code"
    assert autotune._vmem_trigger(RuntimeError("connection reset")) is None
    assert autotune._is_vmem_error(RuntimeError("VMEM overflow"))
    assert not autotune._is_vmem_error(RuntimeError("RESOURCE_EXHAUSTED: HBM"))


class _ScriptedJit:
    """jax stand-in whose jit ignores the traced fn and returns a
    scripted g — the only way to make an error first appear in
    _measure's TIMED loop (a real jit never re-executes Python after
    the warm-up compile, so a scripted failure can't fire there)."""

    def __init__(self, g):
        self._g = g

    def jit(self, f):
        return self._g


def test_timed_loop_vmem_error_translates_to_block_config(monkeypatch):
    import jax.numpy as jnp

    calls = {"n": 0}

    def scripted(carry, n):
        calls["n"] += 1
        if calls["n"] > 2:  # both warm-ups succeed; first timed call dies
            raise RuntimeError(
                "Scoped allocation with size 123 exceeded scoped vmem limit")
        return 0.0

    monkeypatch.setattr(autotune, "jax", _ScriptedJit(scripted))
    q = k = v = jnp.zeros((1, 8, 1, 8), jnp.float32)
    with pytest.raises(autotune.BlockConfigError):
        autotune._measure(lambda *c: c, q, k, v)
    assert calls["n"] == 3


def test_timed_loop_non_vmem_error_propagates(monkeypatch):
    import jax.numpy as jnp

    calls = {"n": 0}

    def scripted(carry, n):
        calls["n"] += 1
        if calls["n"] > 2:
            raise RuntimeError("tunnel reset by peer")
        return 0.0

    monkeypatch.setattr(autotune, "jax", _ScriptedJit(scripted))
    q = k = v = jnp.zeros((1, 8, 1, 8), jnp.float32)
    with pytest.raises(RuntimeError, match="tunnel reset"):
        autotune._measure(lambda *c: c, q, k, v)
