"""Behavioral tests for deferred initialization.

Covers the semantics the reference documents but never tests
(docs/src/deferred_init.rst:176-207 "Common Failure Patterns", the
in-place/view replay engine deferred_init.cc:502-663, and the
materialize_module API deferred_init.py:49-87).
"""

import pytest
import torch
import torch.nn as nn

from torchdistx_tpu.deferred_init import (
    deferred_init,
    materialize_module,
    materialize_tensor,
)
from torchdistx_tpu.fake import is_fake


class TestBasics:
    def test_linear(self):
        m = deferred_init(nn.Linear, 10, 20)
        assert is_fake(m.weight) and is_fake(m.bias)
        materialize_module(m)
        assert not is_fake(m.weight)
        assert isinstance(m.weight, nn.Parameter)
        assert m.weight.requires_grad
        y = m(torch.randn(3, 10))
        assert y.shape == (3, 20)

    def test_materialize_tensor_passthrough_for_real(self):
        # The one real test of the reference suite
        # (tests/python/test_deferred_init.py:12-17).
        t = torch.ones(10)
        assert materialize_tensor(t) is t

    def test_materialize_single_tensor(self):
        m = deferred_init(nn.Linear, 4, 4)
        w = materialize_tensor(m.weight)
        assert not is_fake(w)
        assert w.shape == (4, 4)
        assert isinstance(w, nn.Parameter)

    def test_double_materialize_raises(self):
        def make():
            return torch.full((3,), 7.0)

        t = deferred_init(make)
        materialize_tensor(t)
        with pytest.raises(ValueError, match="already been materialized"):
            materialize_tensor(t)

    def test_kwargs_forwarded(self):
        m = deferred_init(nn.Linear, 4, 4, bias=False)
        assert m.bias is None


class TestEagerParity:
    """Replay must reproduce eager init bitwise under a fixed seed."""

    def _check(self, ctor, *args, **kwargs):
        torch.manual_seed(1234)
        eager = ctor(*args, **kwargs)
        torch.manual_seed(1234)
        deferred = deferred_init(ctor, *args, **kwargs)
        materialize_module(deferred)
        for (n1, p1), (n2, p2) in zip(
            eager.named_parameters(), deferred.named_parameters()
        ):
            assert n1 == n2
            assert torch.equal(p1, p2), n1
        for (n1, b1), (n2, b2) in zip(eager.named_buffers(), deferred.named_buffers()):
            assert torch.equal(b1, b2), n1

    def test_linear(self):
        self._check(nn.Linear, 16, 32)

    def test_embedding(self):
        self._check(nn.Embedding, 100, 16)

    def test_conv(self):
        self._check(nn.Conv2d, 3, 8, 3)

    def test_layernorm(self):
        self._check(nn.LayerNorm, 16)

    def test_batchnorm_with_buffers(self):
        self._check(nn.BatchNorm2d, 8)

    def test_multihead_attention(self):
        self._check(nn.MultiheadAttention, 32, 4)

    def test_sequential_mlp(self):
        self._check(
            lambda: nn.Sequential(
                nn.Linear(8, 16), nn.LayerNorm(16), nn.GELU(), nn.Linear(16, 4)
            )
        )

    def test_transformer_encoder_layer(self):
        self._check(lambda: nn.TransformerEncoderLayer(32, 4, 64, batch_first=True))


class TestInPlaceAndViews:
    def test_in_place_chain(self):
        def make():
            w = torch.empty(4)
            w.fill_(1.0)
            w.add_(2.0)
            w.mul_(3.0)
            return w

        t = deferred_init(make)
        assert torch.equal(materialize_tensor(t), torch.full((4,), 9.0))

    def test_in_place_through_view(self):
        def make():
            w = torch.empty(4, 4)
            w.fill_(1.0)
            v = w[0]
            v.add_(5.0)
            w.mul_(2.0)
            return w, v

        w, v = deferred_init(make)
        rw = materialize_tensor(w)
        assert rw[0, 0].item() == 12.0  # (1+5)*2
        assert rw[1, 1].item() == 2.0

    def test_view_sees_later_base_mutation(self):
        # Materializing only the VIEW must replay the later in-place op on
        # its base (eager semantics; found by the replay fuzzer). The
        # mutation node depends on the base's producer, not the view node,
        # so the dependents-only walk of the reference missed it.
        def make():
            w = torch.full((4, 3), -3.0)
            v = w[2]
            w.mul_(-1.0)
            return v

        v = deferred_init(make)
        rv = materialize_tensor(v)
        assert torch.equal(rv, torch.full((3,), 3.0))

    def test_view_materialization(self):
        def make():
            w = torch.empty(4, 4)
            w.fill_(3.0)
            return w.view(16)

        v = deferred_init(make)
        rv = materialize_tensor(v)
        assert rv.shape == (16,)
        assert torch.equal(rv, torch.full((16,), 3.0))

    def test_dead_view_recording_survives(self):
        # View keep-alive (deferred_init.cc:427-458): the mutation through
        # a view must replay even after the view fake is collected.
        import gc

        def make():
            w = torch.empty(4)
            w.fill_(1.0)
            v = w[:2]
            v.add_(10.0)
            return w

        w = deferred_init(make)
        gc.collect()
        rw = materialize_tensor(w)
        assert rw[0].item() == 11.0
        assert rw[3].item() == 1.0


class TestExternalTensors:
    def test_external_value_used(self):
        ext = torch.tensor([1.0, 2.0, 3.0])

        def make():
            return torch.zeros(3) + ext

        t = deferred_init(make)
        assert torch.equal(materialize_tensor(t), ext)

    def test_version_counter_rejection(self):
        # docs/src/deferred_init.rst:176-207: mutating an external arg
        # after recording must fail replay.
        ext = torch.ones(3)

        def make():
            return torch.zeros(3) + ext

        t = deferred_init(make)
        ext.add_(1)
        with pytest.raises(RuntimeError, match="modified in place"):
            materialize_tensor(t)

    def test_inference_tensor_rejection(self):
        with torch.inference_mode():
            ext = torch.ones(3)

        def make():
            return torch.zeros(3) + ext

        t = deferred_init(make)
        with pytest.raises(RuntimeError, match="inference"):
            materialize_tensor(t)


class TestTerminalOps:
    def test_item_materializes_early(self):
        def make():
            t = torch.ones(3)
            s = t.sum().item()  # value-dependent control flow
            assert s == 3.0
            return torch.full((2,), s)

        t = deferred_init(make)
        assert torch.equal(materialize_tensor(t), torch.full((2,), 3.0))


class TestMaterializeModule:
    def test_recursion_and_buffers(self):
        m = deferred_init(
            lambda: nn.Sequential(nn.Linear(4, 8), nn.BatchNorm1d(8))
        )
        materialize_module(m)
        assert not any(is_fake(p) for p in m.parameters())
        assert not any(is_fake(b) for b in m.buffers())

    def test_buffers_only(self):
        m = deferred_init(nn.BatchNorm1d, 8)
        materialize_module(m, buffers_only=True)
        assert is_fake(m.weight)
        assert not is_fake(m.running_mean)

    def test_check_fn_gates_submodules(self):
        m = deferred_init(
            lambda: nn.Sequential(nn.Linear(4, 8), nn.Linear(8, 4))
        )
        materialize_module(m, check_fn=lambda mod: not isinstance(mod, nn.Linear))
        assert is_fake(m[0].weight) and is_fake(m[1].weight)
        materialize_module(m, check_fn=lambda mod: True)
        assert not is_fake(m[0].weight)

    def test_weight_tying_shared_materialization(self):
        # Improvement over the reference: tied fakes materialize once.
        def make():
            emb = nn.Embedding(32, 8)
            head = nn.Linear(8, 32, bias=False)
            head.weight = emb.weight
            return nn.ModuleDict({"emb": emb, "head": head})

        m = deferred_init(make)
        assert m["head"].weight is m["emb"].weight
        materialize_module(m)
        assert m["head"].weight is m["emb"].weight
        assert not is_fake(m["head"].weight)

    def test_partial_then_full(self):
        m = deferred_init(lambda: nn.Sequential(nn.Linear(4, 4), nn.Linear(4, 4)))
        materialize_module(m[0])
        assert not is_fake(m[0].weight)
        assert is_fake(m[1].weight)
        materialize_module(m)
        assert not is_fake(m[1].weight)


class TestDeviceClaims:
    def test_tpu_claimed_replay_on_cpu(self):
        def make():
            return torch.ones(3, device="tpu")

        t = deferred_init(make)
        assert t.device.type == "tpu"
        r = materialize_tensor(t)
        assert r.device.type == "cpu"
        assert torch.equal(r, torch.ones(3))


class TestRngOrderIndependence:
    def test_module_order_parity(self):
        # Whole-module materialization replays in recorded order, so RNG
        # consumption matches eager even when submodule iteration order
        # differs from construction order.
        def ctor():
            a = nn.Linear(8, 8)
            b = nn.Linear(8, 8)
            return nn.ModuleDict({"b": b, "a": a})  # reversed registration

        torch.manual_seed(7)
        eager = ctor()
        torch.manual_seed(7)
        deferred = deferred_init(ctor)
        materialize_module(deferred)
        for (n1, p1), (n2, p2) in zip(
            eager.named_parameters(), deferred.named_parameters()
        ):
            assert torch.equal(p1, p2), n1
