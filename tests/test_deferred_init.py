"""Behavioral tests for deferred initialization.

Covers the semantics the reference documents but never tests
(docs/src/deferred_init.rst:176-207 "Common Failure Patterns", the
in-place/view replay engine deferred_init.cc:502-663, and the
materialize_module API deferred_init.py:49-87).
"""

import pytest
import torch
import torch.nn as nn

from torchdistx_tpu.deferred_init import (
    deferred_init,
    materialize_module,
    materialize_tensor,
)
from torchdistx_tpu.fake import is_fake


class TestBasics:
    def test_linear(self):
        m = deferred_init(nn.Linear, 10, 20)
        assert is_fake(m.weight) and is_fake(m.bias)
        materialize_module(m)
        assert not is_fake(m.weight)
        assert isinstance(m.weight, nn.Parameter)
        assert m.weight.requires_grad
        y = m(torch.randn(3, 10))
        assert y.shape == (3, 20)

    def test_materialize_tensor_passthrough_for_real(self):
        # The one real test of the reference suite
        # (tests/python/test_deferred_init.py:12-17).
        t = torch.ones(10)
        assert materialize_tensor(t) is t

    def test_materialize_single_tensor(self):
        m = deferred_init(nn.Linear, 4, 4)
        w = materialize_tensor(m.weight)
        assert not is_fake(w)
        assert w.shape == (4, 4)
        assert isinstance(w, nn.Parameter)

    def test_double_materialize_raises(self):
        def make():
            return torch.full((3,), 7.0)

        t = deferred_init(make)
        materialize_tensor(t)
        with pytest.raises(ValueError, match="already been materialized"):
            materialize_tensor(t)

    def test_kwargs_forwarded(self):
        m = deferred_init(nn.Linear, 4, 4, bias=False)
        assert m.bias is None


class TestEagerParity:
    """Replay must reproduce eager init bitwise under a fixed seed."""

    def _check(self, ctor, *args, **kwargs):
        torch.manual_seed(1234)
        eager = ctor(*args, **kwargs)
        torch.manual_seed(1234)
        deferred = deferred_init(ctor, *args, **kwargs)
        materialize_module(deferred)
        for (n1, p1), (n2, p2) in zip(
            eager.named_parameters(), deferred.named_parameters()
        ):
            assert n1 == n2
            assert torch.equal(p1, p2), n1
        for (n1, b1), (n2, b2) in zip(eager.named_buffers(), deferred.named_buffers()):
            assert torch.equal(b1, b2), n1

    def test_linear(self):
        self._check(nn.Linear, 16, 32)

    def test_embedding(self):
        self._check(nn.Embedding, 100, 16)

    def test_conv(self):
        self._check(nn.Conv2d, 3, 8, 3)

    def test_layernorm(self):
        self._check(nn.LayerNorm, 16)

    def test_batchnorm_with_buffers(self):
        self._check(nn.BatchNorm2d, 8)

    def test_multihead_attention(self):
        self._check(nn.MultiheadAttention, 32, 4)

    def test_sequential_mlp(self):
        self._check(
            lambda: nn.Sequential(
                nn.Linear(8, 16), nn.LayerNorm(16), nn.GELU(), nn.Linear(16, 4)
            )
        )

    def test_transformer_encoder_layer(self):
        self._check(lambda: nn.TransformerEncoderLayer(32, 4, 64, batch_first=True))


class TestInPlaceAndViews:
    def test_in_place_chain(self):
        def make():
            w = torch.empty(4)
            w.fill_(1.0)
            w.add_(2.0)
            w.mul_(3.0)
            return w

        t = deferred_init(make)
        assert torch.equal(materialize_tensor(t), torch.full((4,), 9.0))

    def test_in_place_through_view(self):
        def make():
            w = torch.empty(4, 4)
            w.fill_(1.0)
            v = w[0]
            v.add_(5.0)
            w.mul_(2.0)
            return w, v

        w, v = deferred_init(make)
        rw = materialize_tensor(w)
        assert rw[0, 0].item() == 12.0  # (1+5)*2
        assert rw[1, 1].item() == 2.0

    def test_view_sees_later_base_mutation(self):
        # Materializing only the VIEW must replay the later in-place op on
        # its base (eager semantics; found by the replay fuzzer). The
        # mutation node depends on the base's producer, not the view node,
        # so the dependents-only walk of the reference missed it.
        def make():
            w = torch.full((4, 3), -3.0)
            v = w[2]
            w.mul_(-1.0)
            return v

        v = deferred_init(make)
        rv = materialize_tensor(v)
        assert torch.equal(rv, torch.full((3,), 3.0))

    def test_view_materialization(self):
        def make():
            w = torch.empty(4, 4)
            w.fill_(3.0)
            return w.view(16)

        v = deferred_init(make)
        rv = materialize_tensor(v)
        assert rv.shape == (16,)
        assert torch.equal(rv, torch.full((16,), 3.0))

    def test_dead_view_recording_survives(self):
        # View keep-alive (deferred_init.cc:427-458): the mutation through
        # a view must replay even after the view fake is collected.
        import gc

        def make():
            w = torch.empty(4)
            w.fill_(1.0)
            v = w[:2]
            v.add_(10.0)
            return w

        w = deferred_init(make)
        gc.collect()
        rw = materialize_tensor(w)
        assert rw[0].item() == 11.0
        assert rw[3].item() == 1.0


class TestExternalTensors:
    def test_external_value_used(self):
        ext = torch.tensor([1.0, 2.0, 3.0])

        def make():
            return torch.zeros(3) + ext

        t = deferred_init(make)
        assert torch.equal(materialize_tensor(t), ext)

    def test_version_counter_rejection(self):
        # docs/src/deferred_init.rst:176-207: mutating an external arg
        # after recording must fail replay.
        ext = torch.ones(3)

        def make():
            return torch.zeros(3) + ext

        t = deferred_init(make)
        ext.add_(1)
        with pytest.raises(RuntimeError, match="modified in place"):
            materialize_tensor(t)

    def test_inference_tensor_rejection(self):
        with torch.inference_mode():
            ext = torch.ones(3)

        def make():
            return torch.zeros(3) + ext

        t = deferred_init(make)
        with pytest.raises(RuntimeError, match="inference"):
            materialize_tensor(t)


class TestTerminalOps:
    def test_item_materializes_early(self):
        def make():
            t = torch.ones(3)
            s = t.sum().item()  # value-dependent control flow
            assert s == 3.0
            return torch.full((2,), s)

        t = deferred_init(make)
        assert torch.equal(materialize_tensor(t), torch.full((2,), 3.0))


class TestMaterializeModule:
    def test_recursion_and_buffers(self):
        m = deferred_init(
            lambda: nn.Sequential(nn.Linear(4, 8), nn.BatchNorm1d(8))
        )
        materialize_module(m)
        assert not any(is_fake(p) for p in m.parameters())
        assert not any(is_fake(b) for b in m.buffers())

    def test_buffers_only(self):
        m = deferred_init(nn.BatchNorm1d, 8)
        materialize_module(m, buffers_only=True)
        assert is_fake(m.weight)
        assert not is_fake(m.running_mean)

    def test_check_fn_gates_submodules(self):
        m = deferred_init(
            lambda: nn.Sequential(nn.Linear(4, 8), nn.Linear(8, 4))
        )
        materialize_module(m, check_fn=lambda mod: not isinstance(mod, nn.Linear))
        assert is_fake(m[0].weight) and is_fake(m[1].weight)
        materialize_module(m, check_fn=lambda mod: True)
        assert not is_fake(m[0].weight)

    def test_weight_tying_shared_materialization(self):
        # Improvement over the reference: tied fakes materialize once.
        def make():
            emb = nn.Embedding(32, 8)
            head = nn.Linear(8, 32, bias=False)
            head.weight = emb.weight
            return nn.ModuleDict({"emb": emb, "head": head})

        m = deferred_init(make)
        assert m["head"].weight is m["emb"].weight
        materialize_module(m)
        assert m["head"].weight is m["emb"].weight
        assert not is_fake(m["head"].weight)

    def test_partial_then_full(self):
        m = deferred_init(lambda: nn.Sequential(nn.Linear(4, 4), nn.Linear(4, 4)))
        materialize_module(m[0])
        assert not is_fake(m[0].weight)
        assert is_fake(m[1].weight)
        materialize_module(m)
        assert not is_fake(m[1].weight)


class TestDeviceClaims:
    def test_tpu_claimed_replay_on_cpu(self):
        def make():
            return torch.ones(3, device="tpu")

        t = deferred_init(make)
        assert t.device.type == "tpu"
        r = materialize_tensor(t)
        assert r.device.type == "cpu"
        assert torch.equal(r, torch.ones(3))


class TestRngOrderIndependence:
    def test_module_order_parity(self):
        # Whole-module materialization replays in recorded order, so RNG
        # consumption matches eager even when submodule iteration order
        # differs from construction order.
        def ctor():
            a = nn.Linear(8, 8)
            b = nn.Linear(8, 8)
            return nn.ModuleDict({"b": b, "a": a})  # reversed registration

        torch.manual_seed(7)
        eager = ctor()
        torch.manual_seed(7)
        deferred = deferred_init(ctor)
        materialize_module(deferred)
        for (n1, p1), (n2, p2) in zip(
            eager.named_parameters(), deferred.named_parameters()
        ):
            assert torch.equal(p1, p2), n1


class TestSetData:
    """`.data` reads/writes bypass the dispatcher; the reference proxies
    them via VariableHooks (deferred_init.cc:908-1135). The fake frontend
    reroutes them through a Python property (fake.FakeTensor.data) and a
    synthetic `tdx::set_data` replay op — proven here by eager parity."""

    def _parity(self, ctor):
        torch.manual_seed(7)
        eager = ctor()
        torch.manual_seed(7)
        d = deferred_init(ctor)
        materialize_module(d)
        for (n1, p1), (n2, p2) in zip(
            eager.named_parameters(), d.named_parameters()
        ):
            assert n1 == n2
            assert torch.equal(p1, p2), n1
        for (n1, b1), (n2, b2) in zip(eager.named_buffers(), d.named_buffers()):
            assert torch.equal(b1, b2), n1

    def test_data_inplace_normal(self):
        # The HF `_init_weights` idiom: p.data.normal_().
        class M(nn.Module):
            def __init__(self):
                super().__init__()
                self.lin = nn.Linear(8, 8)
                self.lin.weight.data.normal_(mean=0.0, std=0.02)
                self.lin.bias.data.zero_()

        self._parity(M)

    def test_data_inplace_trunc_normal(self):
        class M(nn.Module):
            def __init__(self):
                super().__init__()
                self.emb = nn.Embedding(16, 8)
                nn.init.trunc_normal_(self.emb.weight.data, std=0.02)

        self._parity(M)

    def test_data_assignment_real_rhs(self):
        # m.weight.data = <computed real tensor>; the rhs here is a fake
        # recorded from seeded RNG, so parity covers the value path.
        class M(nn.Module):
            def __init__(self):
                super().__init__()
                self.lin = nn.Linear(4, 4, bias=False)
                self.lin.weight.data = torch.randn(4, 4) * 0.5

        self._parity(M)

    def test_data_assignment_then_inplace(self):
        # Mutations through the new storage after `p.data = w` must be
        # visible through p (true aliasing after the rebind).
        class M(nn.Module):
            def __init__(self):
                super().__init__()
                self.lin = nn.Linear(3, 3, bias=False)
                w = torch.zeros(3, 3)
                self.lin.weight.data = w
                w.fill_(2.5)

        self._parity(M)

    def test_parameter_of_fake(self):
        # nn.Parameter(<fake>) — Parameter construction bypasses dispatch.
        class M(nn.Module):
            def __init__(self):
                super().__init__()
                self.w = nn.Parameter(torch.randn(3, 5))

        self._parity(M)

    def test_data_read_is_fake_and_recorded(self):
        from torchdistx_tpu.fake import is_fake as _isf

        def make():
            w = torch.full((4,), 3.0)
            return w.data * 2.0

        t = deferred_init(make)
        assert _isf(t)
        assert torch.equal(materialize_tensor(t), torch.full((4,), 6.0))

    def test_shape_changing_set_data_materializes(self):
        # torch's set_data allows ANY metadata change
        # (deferred_init.cc:930-971); the wrapper re-wraps in place
        # (VERDICT r2 missing #2 — round 2 raised here).
        def make():
            lin = nn.Linear(4, 4)
            lin.weight.data = torch.zeros(2, 2)
            return lin

        m = deferred_init(make)
        assert m.weight.shape == (2, 2)
        assert torch.equal(materialize_tensor(m.weight), torch.zeros(2, 2))

    def test_dtype_changing_set_data_materializes(self):
        def make():
            q = nn.Parameter(torch.zeros(4))
            q.data = torch.ones(4, dtype=torch.float64)
            return q

        q = deferred_init(make)
        assert q.dtype == torch.float64
        out = materialize_tensor(q)
        assert out.dtype == torch.float64
        assert torch.equal(out, torch.ones(4, dtype=torch.float64))


class TestThreadLocalState:
    """Full per-op TLS capture/restore (counterpart of the reference's
    at::ThreadLocalState capture, deferred_init.cc:207, 263)."""

    def test_record_under_autocast_replays_identically(self):
        class M(nn.Module):
            def __init__(self):
                super().__init__()
                a = torch.randn(4, 4)
                b = torch.randn(4, 4)
                self.register_buffer("proj", torch.mm(a, b))

        def ctor():
            with torch.autocast("cpu"):
                return M()

        torch.manual_seed(3)
        eager = ctor()
        torch.manual_seed(3)
        d = deferred_init(ctor)
        assert d.proj.dtype == torch.bfloat16  # autocast applied at record
        materialize_module(d)  # replayed OUTSIDE the autocast region
        assert eager.proj.dtype == torch.bfloat16
        assert d.proj.dtype == torch.bfloat16
        assert torch.equal(d.proj, eager.proj)

    def test_materialize_inside_foreign_autocast_region(self):
        # Recorded WITHOUT autocast; replay inside someone else's autocast
        # region must restore the captured (disabled) state, or the mm
        # replays as bfloat16 and diverges from its recorded f32 meta.
        class M(nn.Module):
            def __init__(self):
                super().__init__()
                self.register_buffer("proj", torch.mm(torch.ones(4, 4), torch.ones(4, 4)))

        d = deferred_init(M)
        assert d.proj.dtype == torch.float32
        with torch.autocast("cpu"):
            materialize_module(d)
        assert d.proj.dtype == torch.float32
        assert torch.equal(d.proj, torch.full((4, 4), 4.0))

    def test_default_dtype_captured(self):
        # A factory recorded under a non-default default dtype must replay
        # with that dtype even after the ambient default was restored.
        def make():
            torch.set_default_dtype(torch.float64)
            try:
                return torch.empty(3).fill_(1.5)
            finally:
                torch.set_default_dtype(torch.float32)

        t = deferred_init(make)
        assert t.dtype == torch.float64
        out = materialize_tensor(t)
        assert out.dtype == torch.float64
        assert torch.equal(out, torch.full((3,), 1.5, dtype=torch.float64))

    def test_grad_mode_still_captured(self):
        def make():
            with torch.no_grad():
                w = torch.ones(3)
                w.add_(1.0)
            return w

        t = deferred_init(make)
        assert torch.equal(materialize_tensor(t), torch.full((3,), 2.0))


class TestNoDeferredInit:
    """Public counterpart of the reference's NoDeferredInit guard
    (deferred_init.h:35-43)."""

    def test_real_tensors_inside_guard(self):
        from torchdistx_tpu.deferred_init import no_deferred_init

        captured = {}

        class M(nn.Module):
            def __init__(self):
                super().__init__()
                self.lin = nn.Linear(4, 4)
                with no_deferred_init():
                    table = torch.arange(8.0)  # build-time constant: real
                captured["table"] = table
                self.register_buffer("table", table)

        m = deferred_init(M)
        assert not is_fake(captured["table"])
        assert is_fake(m.lin.weight)
        materialize_module(m)
        assert torch.equal(m.table, torch.arange(8.0))

    def test_session_rng_numbering_survives_guard(self):
        # A guard in the middle of a recording must not shift the
        # session-relative key numbering of later ops (jax-bridge RNG).
        from torchdistx_tpu.deferred_init import no_deferred_init
        from torchdistx_tpu.jax_bridge import materialize_params_jax
        import numpy as np

        def make(use_guard):
            a = torch.empty(8)
            a.normal_()
            if use_guard:
                with no_deferred_init():
                    torch.ones(3)  # real; consumes nothing recordable
            b = torch.empty(8)
            b.normal_()
            return a, b

        ra, rb = deferred_init(make, False)
        ga, gb = deferred_init(make, True)
        ref = materialize_params_jax({"a": ra, "b": rb}, seed=5)
        got = materialize_params_jax({"a": ga, "b": gb}, seed=5)
        assert np.array_equal(np.asarray(ref["a"]), np.asarray(got["a"]))
        assert np.array_equal(np.asarray(ref["b"]), np.asarray(got["b"]))

    def test_guard_outside_recording_is_noop(self):
        from torchdistx_tpu.deferred_init import no_deferred_init

        with no_deferred_init():
            t = torch.ones(3)
        assert not is_fake(t)

    def test_guard_with_foreign_mode_above(self):
        # The guard must not disturb an unrelated TorchDispatchMode that
        # is active above the deferred mode (it suspends via a flag, not
        # by popping torch's LIFO mode stack).
        from torch.utils._python_dispatch import TorchDispatchMode

        from torchdistx_tpu.deferred_init import (
            enable_deferred_init,
            no_deferred_init,
        )

        seen = {"n": 0}

        class Counter(TorchDispatchMode):
            def __torch_dispatch__(self, func, types, args=(), kwargs=None):
                seen["n"] += 1
                return func(*args, **(kwargs or {}))

        enable_deferred_init(True)
        try:
            with Counter():
                fake_before = torch.ones(2)
                with no_deferred_init():
                    real = torch.ones(3)  # foreign mode still sees this
                fake_after = torch.ones(2)
        finally:
            enable_deferred_init(False)
        assert is_fake(fake_before) and is_fake(fake_after)
        assert not is_fake(real)
        assert seen["n"] >= 3  # Counter stayed active throughout


class TestDeepcopy:
    def test_deepcopy_inside_region_records(self):
        import copy

        def make():
            lin = nn.Linear(4, 4)
            twin = copy.deepcopy(lin)
            twin.weight.data.mul_(2.0)
            return lin, twin

        lin, twin = deferred_init(make)
        materialize_module(lin)
        materialize_module(twin)
        assert torch.equal(twin.weight, lin.weight * 2.0)
        assert isinstance(twin.weight, nn.Parameter)

    def test_deepcopy_outside_region_raises_actionably(self):
        import copy

        m = deferred_init(nn.Linear, 4, 4)
        with pytest.raises(RuntimeError, match="outside its\n?.*deferred-init region|deferred-init region"):
            copy.deepcopy(m)

    def test_deepcopy_preserves_view_storage_sharing(self):
        import copy

        def make():
            t = torch.zeros(6)
            d = copy.deepcopy({"a": t, "b": t[:2]})
            d["a"].fill_(3.0)  # must be visible through the copied view
            return d["a"], d["b"], t

        a, b, t = deferred_init(make)
        ra = materialize_tensor(a)
        rb = materialize_tensor(b)
        rt = materialize_tensor(t)
        assert torch.equal(ra, torch.full((6,), 3.0))
        assert torch.equal(rb, torch.full((2,), 3.0))  # shared in the copy
        assert torch.equal(rt, torch.zeros(6))  # original untouched

    def test_rng_inside_guard_stays_stream_aligned(self):
        # A real draw inside no_deferred_init() must consume the
        # generator AFTER all pending recorded draws (eager order).
        from torchdistx_tpu.deferred_init import no_deferred_init

        class M(nn.Module):
            def __init__(self):
                super().__init__()
                self.lin = nn.Linear(8, 8, bias=False)
                with no_deferred_init():
                    self.r = torch.randn(4)

        torch.manual_seed(21)
        eager_lin = nn.Linear(8, 8, bias=False)
        eager_r = torch.randn(4)
        torch.manual_seed(21)
        d = deferred_init(M)
        materialize_module(d)
        assert torch.equal(d.r, eager_r)
        assert torch.equal(d.lin.weight, eager_lin.weight)


class TestValueReads:
    """tolist()/numpy()/item() on recorded fakes — the reference documents
    these as unsupported failure patterns (deferred_init.rst:204-207); the
    early-replay hatch covers them (snapshot semantics)."""

    def test_item_method(self):
        t = deferred_init(lambda: torch.full((), 4.25))
        assert t.item() == 4.25
        # recording continues after the early read
        u = deferred_init(lambda: torch.full((2,), 1.0) * 2)
        assert torch.equal(materialize_tensor(u), torch.full((2,), 2.0))

    def test_tolist_and_numpy(self):
        import numpy as np

        def make():
            w = torch.arange(6.0).reshape(2, 3)
            vals = w.tolist()  # value-dependent init logic
            assert vals[1][2] == 5.0
            arr = w.numpy()
            assert arr.shape == (2, 3)
            return w * torch.tensor(vals)  # keep recording afterwards

        t = deferred_init(make)
        out = materialize_tensor(t)
        ref = torch.arange(6.0).reshape(2, 3)
        assert torch.equal(out, ref * ref)

    def test_float_int_conversions(self):
        t = deferred_init(lambda: torch.full((), 2.5))
        assert float(t) == 2.5
        i = deferred_init(lambda: torch.full((), 3, dtype=torch.int64))
        assert int(i) == 3

    def test_plain_fake_mode_still_raises(self):
        from torchdistx_tpu.fake import fake_mode

        with fake_mode():
            f = torch.ones(3)
        with pytest.raises(RuntimeError, match="have no storage"):
            f.tolist()
        with pytest.raises(RuntimeError, match="have no storage"):
            bool(f.sum())

    def test_dead_fake_rng_draw_still_flushes_in_order(self):
        # A recorded draw whose fake died before the flush must still
        # replay at its stream position (strong refs in the registry).
        from torchdistx_tpu.deferred_init import no_deferred_init

        class M(nn.Module):
            def __init__(self):
                super().__init__()
                tmp = torch.randn(4)  # fake dies at end of __init__... 
                del tmp  # ...explicitly, before the guard draw
                import gc; gc.collect()
                with no_deferred_init():
                    self.r = torch.randn(3)

        torch.manual_seed(31)
        _ = torch.randn(4)
        eager_r = torch.randn(3)
        torch.manual_seed(31)
        d = deferred_init(M)
        assert torch.equal(d.r, eager_r)

    def test_value_read_after_region_stays_aligned(self):
        def make():
            a = torch.randn(4)
            b = torch.randn(4)
            return a, b

        torch.manual_seed(41)
        ea = torch.randn(4); eb = torch.randn(4)
        torch.manual_seed(41)
        a, b = deferred_init(make)
        # read b FIRST, after the region: a's draw must replay before b's
        assert b.tolist() == eb.tolist()
        assert torch.equal(materialize_tensor(a), ea)


class TestSetDataLayoutChanges:
    """Layout-changing ``.data`` assignment re-wraps (soak fuzzer seed
    2160 found the STALE-metadata hazard; the fix is now an impl swap,
    not a rejection) — the wrapper must report the assigned layout so
    composite-op decompositions consult the right contiguity."""

    def test_stride_changing_data_assignment(self):
        import torch

        from torchdistx_tpu.deferred_init import deferred_init, materialize_tensor

        def build():
            a = torch.full((2, 2), 1.0)
            b = torch.full((2, 2), 2.0).t()  # same shape, strides (1, 2)
            a.data = b
            return a.flatten()  # decomposition consults the new layout

        out = deferred_init(build)
        ea = torch.full((2, 2), 1.0)
        ea.data = torch.full((2, 2), 2.0).t()
        torch.testing.assert_close(materialize_tensor(out), ea.flatten())

    def test_non_dense_real_data_assignment(self):
        # The meta must preserve the source's exact strides (empty_like
        # would contiguize and misreport the layout).
        import torch

        from torchdistx_tpu.deferred_init import deferred_init, materialize_tensor

        a = deferred_init(lambda: torch.zeros(2))
        a.data = torch.arange(4.0)[::2]  # strides (2,)
        assert a.stride() == (2,)
        e = torch.zeros(2)
        e.data = torch.arange(4.0)[::2]
        out = materialize_tensor(a)
        assert torch.equal(out, e) and out.stride() == e.stride()
