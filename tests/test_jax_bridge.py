"""Tests for the init-graph → JAX compiler and sharded materialization."""

import numpy as np
import pytest
import torch
import torch.nn as nn

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from torchdistx_tpu.deferred_init import deferred_init
from torchdistx_tpu.jax_bridge import (
    build_init_fn,
    materialize_module_jax,
    materialize_params_jax,
    materialize_tensor_jax,
    named_fake_tensors,
)
from torchdistx_tpu.parallel import ShardingPlan, fsdp_plan, make_mesh


@pytest.fixture(scope="module")
def mesh():
    return make_mesh({"fsdp": 4, "tp": 2})


class TestCompile:
    def test_factory_chain(self):
        def make():
            w = torch.empty(4, 4)
            w.fill_(2.0)
            w.mul_(3.0)
            return w

        t = deferred_init(make)
        arr = materialize_tensor_jax(t)
        assert np.allclose(np.asarray(arr), 6.0)

    def test_dtype(self):
        t = deferred_init(lambda: torch.zeros(3, dtype=torch.bfloat16))
        arr = materialize_tensor_jax(t)
        assert arr.dtype == jnp.bfloat16

    def test_view_scatter(self):
        def make():
            w = torch.empty(4, 4)
            w.fill_(1.0)
            w[0].fill_(9.0)
            return w

        t = deferred_init(make)
        arr = np.asarray(materialize_tensor_jax(t))
        assert arr[0, 0] == 9.0 and arr[1, 1] == 1.0

    def test_slice_scatter(self):
        def make():
            w = torch.empty(6)
            w.zero_()
            w[2:4].add_(5.0)
            return w

        t = deferred_init(make)
        arr = np.asarray(materialize_tensor_jax(t))
        assert list(arr) == [0, 0, 5, 5, 0, 0]

    def test_transpose_view_write(self):
        def make():
            w = torch.empty(2, 3)
            w.fill_(1.0)
            w.t().mul_(2.0)
            return w

        t = deferred_init(make)
        arr = np.asarray(materialize_tensor_jax(t))
        assert arr.shape == (2, 3) and np.allclose(arr, 2.0)

    def test_squeeze_view_scatter(self):
        def make():
            w = torch.empty(1, 4)
            w.zero_()
            w.squeeze(0).fill_(3.0)
            return w

        t = deferred_init(make)
        assert np.allclose(np.asarray(materialize_tensor_jax(t)), 3.0)

    def test_expand_neg_one_leading_dim(self):
        def make():
            b = torch.empty(3)
            b.fill_(2.0)
            return b.expand(4, -1) + 0.0

        t = deferred_init(make)
        arr = np.asarray(materialize_tensor_jax(t))
        assert arr.shape == (4, 3) and np.allclose(arr, 2.0)

    def test_random_overload(self):
        def make():
            w = torch.empty(64)
            w.random_(0, 5)
            return w

        t = deferred_init(make)
        arr = np.asarray(materialize_tensor_jax(t))
        assert ((arr >= 0) & (arr < 5)).all()

    def test_external_tensor_constant(self):
        ext = torch.tensor([1.0, 2.0, 3.0])
        t = deferred_init(lambda: torch.zeros(3) + ext)
        arr = np.asarray(materialize_tensor_jax(t))
        assert np.allclose(arr, [1, 2, 3])

    def test_terminal_op_constant(self):
        def make():
            s = torch.ones(3).sum().item()
            return torch.full((2,), s)

        t = deferred_init(make)
        assert np.allclose(np.asarray(materialize_tensor_jax(t)), 3.0)

    def test_missing_op_actionable_error(self):
        # A real-tensor-consuming op outside the table (use angle-y op).
        def make():
            w = torch.empty(3, 3)
            w.fill_(1.0)
            return torch.linalg.inv(w + torch.eye(3))

        t = deferred_init(make)
        with pytest.raises(NotImplementedError, match="no JAX lowering"):
            materialize_tensor_jax(t)

    def test_rng_statistics(self):
        t = deferred_init(lambda: torch.empty(2000).normal_(1.0, 0.5))
        arr = np.asarray(materialize_tensor_jax(t))
        assert abs(arr.mean() - 1.0) < 0.05
        assert abs(arr.std() - 0.5) < 0.05

    def test_rng_deterministic(self):
        t = deferred_init(lambda: torch.empty(64).uniform_())
        a = np.asarray(materialize_tensor_jax(t, seed=3))
        b = np.asarray(materialize_tensor_jax(t, seed=3))
        c = np.asarray(materialize_tensor_jax(t, seed=4))
        assert np.array_equal(a, b)
        assert not np.array_equal(a, c)

    def test_rng_independent_of_prior_recordings(self):
        # RNG keys are session-relative: the same recording under the same
        # seed yields the same values no matter what the process recorded
        # before (keys fold the per-session op number, not the raw global
        # ordering counter).
        make = lambda: torch.empty(64).uniform_()
        a = np.asarray(materialize_tensor_jax(deferred_init(make), seed=3))
        deferred_init(lambda: torch.zeros(7).add_(1))  # unrelated recording
        b = np.asarray(materialize_tensor_jax(deferred_init(make), seed=3))
        assert np.array_equal(a, b)


class TestShardedMaterialize:
    def test_out_sharding(self, mesh):
        m = deferred_init(nn.Linear, 64, 128)
        p = materialize_module_jax(
            m, mesh=mesh, plan=ShardingPlan([(r"weight", P("tp", "fsdp"))])
        )
        w = p["weight"]
        assert w.shape == (128, 64)
        assert w.sharding.spec == P("tp", "fsdp")
        assert w.addressable_shards[0].data.shape == (64, 16)

    def test_sharding_independent_values(self, mesh):
        m = deferred_init(nn.Linear, 32, 32)
        a = materialize_module_jax(m, seed=7)
        b = materialize_module_jax(m, mesh=mesh, plan=fsdp_plan(min_size=1), seed=7)
        assert np.allclose(np.asarray(a["weight"]), np.asarray(b["weight"]))

    def test_indivisible_dim_falls_back(self, mesh):
        m = deferred_init(nn.Linear, 7, 13)
        with pytest.warns(UserWarning, match="not divisible"):
            p = materialize_module_jax(
                m, mesh=mesh, plan=ShardingPlan([(r"weight", P("fsdp", "tp"))])
            )
        assert p["weight"].shape == (13, 7)

    def test_embedding_padding_idx(self):
        m = deferred_init(nn.Embedding, 50, 16, padding_idx=0)
        p = materialize_module_jax(m)
        assert bool((p["weight"][0] == 0).all())
        assert bool((p["weight"][1] != 0).any())

    def test_tied_weights_once(self):
        def make():
            emb = nn.Embedding(32, 8)
            head = nn.Linear(8, 32, bias=False)
            head.weight = emb.weight
            return nn.ModuleDict({"emb": emb, "head": head})

        m = deferred_init(make)
        fakes = named_fake_tensors(m)
        assert "emb.weight" in fakes and "head.weight" not in fakes

    def test_batchnorm_buffers(self):
        m = deferred_init(nn.BatchNorm1d, 8)
        p = materialize_module_jax(m)
        assert np.allclose(np.asarray(p["running_var"]), 1.0)
        assert np.allclose(np.asarray(p["running_mean"]), 0.0)
        # torch.tensor(0) stays real inside deferred init (the reference's
        # internal_new_from_data bailout, deferred_init.cc:776-785), so it
        # is not part of the fake set.
        assert "num_batches_tracked" not in p
        assert int(m.num_batches_tracked) == 0


class TestMeshHelpers:
    def test_make_mesh_inference(self):
        mesh = make_mesh({"dp": -1, "tp": 2})
        assert mesh.shape["dp"] == 4 and mesh.shape["tp"] == 2

    def test_axis_order(self):
        mesh = make_mesh({"tp": 2, "pp": 2, "dp": 2})
        assert mesh.axis_names == ("pp", "dp", "tp")

    def test_bad_sizes(self):
        with pytest.raises(ValueError):
            make_mesh({"dp": 3, "tp": 2})


class TestTransformerEndToEnd:
    def test_gpt2_sharded(self, mesh):
        from transformers import GPT2Config, GPT2LMHeadModel

        m = deferred_init(GPT2LMHeadModel, GPT2Config(n_layer=2, n_embd=64, n_head=2))
        p = materialize_module_jax(m, mesh=mesh, plan=fsdp_plan(min_size=1024), seed=0)
        assert "transformer.wte.weight" in p
        assert "lm_head.weight" not in p  # tied
        # values finite and initialized
        w = np.asarray(p["transformer.h.0.attn.c_attn.weight"])
        assert np.isfinite(w).all() and w.std() > 0


class TestSyntheticOps:
    def test_set_data_lowering(self):
        # `p.data = w` lowers as a value rebind of the base's box.
        class M(nn.Module):
            def __init__(self):
                super().__init__()
                self.lin = nn.Linear(4, 4, bias=False)
                self.lin.weight.data = torch.full((4, 4), 1.5)

        m = deferred_init(M)
        p = materialize_module_jax(m, seed=0)
        assert np.allclose(np.asarray(p["lin.weight"]), 1.5)

    def test_data_inplace_normal_lowering(self):
        # The HF `_init_weights` idiom through the .data detach view.
        class M(nn.Module):
            def __init__(self):
                super().__init__()
                self.lin = nn.Linear(16, 16, bias=False)
                self.lin.weight.data.normal_(0.0, 0.02)

        m = deferred_init(M)
        p = materialize_module_jax(m, seed=0)
        w = np.asarray(p["lin.weight"])
        assert np.isfinite(w).all()
        assert 0.005 < w.std() < 0.05

    def test_default_dtype_tls_lowering(self):
        # Factories recorded under torch.set_default_dtype(bfloat16)
        # resolve their dtype from the captured per-op TLS.
        def make():
            torch.set_default_dtype(torch.bfloat16)
            try:
                return torch.ones(4)
            finally:
                torch.set_default_dtype(torch.float32)

        t = deferred_init(make)
        arr = materialize_tensor_jax(t)
        assert arr.dtype == jnp.bfloat16

    def test_set_data_then_inplace_through_rhs(self):
        # After `p.data = w`, mutations through w must be visible through
        # p in the JAX lowering too (boxes are aliased, not value-copied).
        class M(nn.Module):
            def __init__(self):
                super().__init__()
                self.lin = nn.Linear(3, 3, bias=False)
                w = torch.zeros(3, 3)
                self.lin.weight.data = w
                w.fill_(2.5)

        m = deferred_init(M)
        p = materialize_module_jax(m, seed=0)
        assert np.allclose(np.asarray(p["lin.weight"]), 2.5)


class TestExportedInit:
    """AOT export: lower the init program cross-platform, serialize,
    reload, run — no retracing at destination (jax_bridge/export.py)."""

    def test_roundtrip_matches_live_materialization(self, tmp_path):
        class M(nn.Module):
            def __init__(self):
                super().__init__()
                self.a = nn.Linear(8, 16)
                self.b = nn.Embedding(32, 8)

        m = deferred_init(M)
        live = materialize_module_jax(m, seed=7)

        m2 = deferred_init(M)
        p = tmp_path / "init.tdxe"
        from torchdistx_tpu.jax_bridge import load_exported_init, save_exported_init

        names = save_exported_init(m2, p, platforms=("tpu", "cpu"))
        run, names2 = load_exported_init(p)
        assert names == names2
        outs = run(jax.random.PRNGKey(7))
        got = dict(zip(names2, outs))
        for k in live:
            assert np.array_equal(np.asarray(live[k]), np.asarray(got[k])), k

    def test_sharded_export_executes_and_matches_live(self):
        # The login-host artifact: export the init SHARDED over the
        # 8-device mesh (for the CPU platform so this host can run it),
        # deserialize, execute — values and shardings must match live
        # sharded materialization.
        from torchdistx_tpu.jax_bridge.export import _MAGIC, export_sharded_init
        import json as _json
        import struct as _struct
        from jax import export as jax_export

        class M(nn.Module):
            def __init__(self):
                super().__init__()
                self.a = nn.Linear(8, 16)
                self.b = nn.Embedding(32, 8)

        mesh = make_mesh({"fsdp": 4, "tp": 2})
        m = deferred_init(M)
        live = materialize_params_jax(
            named_fake_tensors(m), mesh=mesh, plan=fsdp_plan(min_size=16), seed=7
        )

        m2 = deferred_init(M)
        payload, names = export_sharded_init(
            m2, mesh=mesh, plan=fsdp_plan(min_size=16), platforms=("cpu",)
        )
        assert payload[:8] == _MAGIC
        (hlen,) = _struct.unpack("<I", payload[8:12])
        header = _json.loads(payload[12 : 12 + hlen].decode())
        assert header["names"] == names
        assert header["nr_devices"] == 8
        exp = jax_export.deserialize(payload[12 + hlen :])
        assert exp.nr_devices == 8
        # The pod side: load_exported_init handles the n-device calling
        # context itself (jit over the first n local devices).
        import tempfile
        from pathlib import Path

        from torchdistx_tpu.jax_bridge import load_exported_init

        with tempfile.TemporaryDirectory() as d:
            p = Path(d) / "sharded.tdxe"
            p.write_bytes(payload)
            run, names2 = load_exported_init(p)
        assert names2 == names
        outs = run(jax.random.PRNGKey(7))
        got = dict(zip(names, outs))
        for k in live:
            assert np.array_equal(np.asarray(live[k]), np.asarray(got[k])), k

    def test_sharded_export_cross_platform_tpu(self):
        # The real direction: a TPU 64-logical-device program generated
        # on this CPU-only host (execution needs the pod; the export
        # must embed the right device count and platform).
        from torchdistx_tpu.jax_bridge.export import export_sharded_init
        import json as _json
        import struct as _struct
        from jax import export as jax_export

        m = deferred_init(nn.Linear, 16, 16)
        mesh = make_mesh({"fsdp": 8})
        payload, names = export_sharded_init(
            {"w": m.weight, "b": m.bias}, mesh=mesh,
            plan=fsdp_plan(min_size=16), platforms=("tpu",),
        )
        (hlen,) = _struct.unpack("<I", payload[8:12])
        assert _json.loads(payload[12 : 12 + hlen].decode())["platforms"] == ["tpu"]
        exp = jax_export.deserialize(payload[12 + hlen :])
        assert exp.nr_devices == 8
        assert tuple(exp.platforms) == ("tpu",)

    def test_sharded_export_too_few_devices_rejected(self, tmp_path):
        # A 999-device program on this 8-device host: friendly error at
        # load, before deserialization (nr_devices rides the header).
        import json as _json
        import struct as _struct

        from torchdistx_tpu.jax_bridge import load_exported_init
        from torchdistx_tpu.jax_bridge.export import _MAGIC

        header = _json.dumps(
            {"names": [], "platforms": ["cpu"], "nr_devices": 999}
        ).encode()
        p = tmp_path / "big.tdxe"
        p.write_bytes(_MAGIC + _struct.pack("<I", len(header)) + header + b"XX")
        with pytest.raises(ValueError, match="999-device"):
            load_exported_init(p)

    def test_bad_file_rejected(self, tmp_path):
        from torchdistx_tpu.jax_bridge import load_exported_init

        p = tmp_path / "junk.tdxe"
        p.write_bytes(b"not an export")
        with pytest.raises(ValueError, match="not a torchdistx_tpu init export"):
            load_exported_init(p)

    def test_truncated_file_rejected(self, tmp_path):
        from torchdistx_tpu.jax_bridge import load_exported_init

        p = tmp_path / "trunc.tdxe"
        p.write_bytes(b"TDXEXP01\x10")  # magic + truncated header length
        with pytest.raises(ValueError):
            load_exported_init(p)

    def test_platform_mismatch_rejected(self, tmp_path):
        from torchdistx_tpu.jax_bridge import load_exported_init, save_exported_init

        def make():
            return torch.ones(3)

        t = deferred_init(make)
        p = tmp_path / "tpu_only.tdxe"
        save_exported_init({"t": t}, p, platforms=("tpu",))
        with pytest.raises(ValueError, match="exported for platforms"):
            load_exported_init(p)  # current backend is cpu


class TestDeepcopyLowering:
    def test_deepcopied_module_lowers(self):
        # FakeTensor.__deepcopy__ emits as_strided views over a storage
        # clone; the bridge's as_strided gather/scatter lowering must
        # reproduce the torch replay values.
        import copy

        class M(nn.Module):
            def __init__(self):
                super().__init__()
                self.a = nn.Linear(8, 8, bias=False)
                self.twin = copy.deepcopy(self.a)
                self.twin.weight.data.mul_(0.5)

        m = deferred_init(M)
        p = materialize_module_jax(m, seed=0)
        assert np.allclose(
            np.asarray(p["twin.weight"]), np.asarray(p["a.weight"]) * 0.5
        )

    def test_deepcopy_of_view_first_lowers_correctly(self):
        # The storage-copy protocol may emit the full-extent as_strided
        # against a VIEW (when the view is deepcopied before its base);
        # the lowering must resolve storage-relative, not view-relative.
        import copy

        def make():
            t = torch.arange(6.0)
            d = copy.deepcopy({"b": t[2:4]})
            return d["b"]

        b = deferred_init(make)
        arr = materialize_tensor_jax(b)
        assert np.array_equal(np.asarray(arr), [2.0, 3.0])

    def test_deepcopy_of_noncontiguous_lowers_correctly(self):
        import copy

        def make():
            t = torch.arange(6.0).reshape(2, 3)
            d = copy.deepcopy({"tt": t.t()})
            return d["tt"]

        tt = deferred_init(make)
        arr = materialize_tensor_jax(tt)
        assert np.array_equal(
            np.asarray(arr), np.arange(6.0).reshape(2, 3).T
        )


class TestLowerInitModule:
    """lower_init_module: the host-side (login-host) half of the north
    star — produce the sharded init program without compiling/executing."""

    def test_lowered_matches_live_materialization(self):
        from torchdistx_tpu.jax_bridge import lower_init_module

        class M(nn.Module):
            def __init__(self):
                super().__init__()
                self.a = nn.Linear(16, 32)
                self.b = nn.Embedding(64, 16)

        m = deferred_init(M)
        mesh = make_mesh({"fsdp": 4, "tp": 2})
        plan = fsdp_plan(min_size=16)
        lowered, names = lower_init_module(m, mesh=mesh, plan=plan)
        assert set(names) == {"a.weight", "a.bias", "b.weight"}
        compiled = lowered.compile()
        values = dict(zip(names, compiled(jax.random.PRNGKey(0))))

        live = materialize_module_jax(m, mesh=mesh, plan=plan, seed=0)
        for n in names:
            np.testing.assert_allclose(
                np.asarray(values[n]), np.asarray(live[n]), rtol=1e-6
            )
            assert values[n].sharding == live[n].sharding

    def test_stablehlo_text_available(self):
        from torchdistx_tpu.jax_bridge import lower_init_module

        m = deferred_init(nn.Linear, 8, 8)
        lowered, _ = lower_init_module(m)
        assert "stablehlo" in lowered.as_text() or "func.func" in lowered.as_text()


class TestLLVMContraction:
    """Soak seed 12013093: XLA CPU codegen contracts fmul+fadd into a
    single-rounded FMA — torch's two eager kernels round twice.  The fix
    (`ops._kernel_boundary`) hides every mul behind a `conditional` whose
    branches compile to separate LLVM functions.  These tests pin both
    the numbers AND the structure, so a future XLA that starts folding
    barrier-predicated conditionals fails loudly here rather than
    silently un-fixing the exactness policy."""

    @staticmethod
    def _make():
        w = torch.arange(12, dtype=torch.float32).reshape(2, 6)
        t = w.div(3.0)
        w = w.clone()
        w.mul_(t)
        w.add_(t)
        return w

    def test_mul_add_double_rounds(self):
        expected = self._make()  # real torch eager: two roundings
        fake = deferred_init(self._make)
        arr = materialize_tensor_jax(fake)
        assert np.array_equal(np.asarray(arr), expected.numpy())

    def test_mul_survives_llvm_contraction(self):
        fake = deferred_init(self._make)
        fn = build_init_fn([fake])
        key = jax.random.PRNGKey(0)
        txt = jax.jit(fn).lower(key).compile().as_text()
        # The POST-optimization HLO must still carry the mul's conditional:
        # if any pass inlined it, contraction is back on the table.
        assert " conditional(" in txt, (
            "the _kernel_boundary conditional was optimized away — LLVM "
            "can contract fmul+fadd again (soak seed 12013093)"
        )


class TestMultiOutputViews:
    def test_split_chunk_alias_lowering(self):
        # aten.split is ONE node with several aliasing view outputs; each
        # lowers to its own lens over the shared base box (multiview),
        # so writes through one chunk are visible through the base.
        from torchdistx_tpu.jax_bridge import materialize_params_jax

        def build():
            a = torch.arange(12, dtype=torch.float32).reshape(6, 2)
            top, bot = a.chunk(2, 0)
            top.mul_(10.0)
            c = bot.clone()
            parts = a.split(2, 0)
            return {"a": a, "top": top, "c": c, "p1": parts[1]}

        eager = build()
        fakes = deferred_init(build)
        arrays = materialize_params_jax(dict(fakes), seed=0)
        for k, t in eager.items():
            np.testing.assert_array_equal(t.numpy(), np.asarray(arrays[k]))

    def test_split_with_sizes_lowering(self):
        from torchdistx_tpu.jax_bridge import materialize_params_jax

        def build():
            a = torch.arange(10, dtype=torch.float32)
            x, y, z = a.split([3, 3, 4], 0)
            y.add_(100.0)
            return {"a": a, "x": x, "z": z}

        eager = build()
        fakes = deferred_init(build)
        arrays = materialize_params_jax(dict(fakes), seed=0)
        for k, t in eager.items():
            np.testing.assert_array_equal(t.numpy(), np.asarray(arrays[k]))


class TestExternalConstantDtypes:
    def test_bf16_external_tensor_stays_bf16(self):
        # An external bf16 tensor entering the recording must become a
        # bf16 constant (to_numpy routes through ml_dtypes.bfloat16): an
        # f32 constant would silently change downstream arithmetic —
        # bf16 + bf16 rounds at 8 mantissa bits, f32 + f32 at 24.
        import torch

        from torchdistx_tpu.deferred_init import deferred_init
        from torchdistx_tpu.fake import is_fake
        from torchdistx_tpu.jax_bridge import materialize_params_jax

        torch.manual_seed(0)
        ext = torch.randn(4, 3, dtype=torch.bfloat16)

        def build():
            a = torch.full((4, 3), 2.0, dtype=torch.bfloat16)
            b = a + ext
            return a, b, b.float()

        eager = build()
        fakes = deferred_init(build)
        arrays = materialize_params_jax(
            {str(i): t for i, t in enumerate(fakes) if is_fake(t)}, seed=0
        )
        import numpy as np

        for k, arr in arrays.items():
            e = eager[int(k)]
            assert str(np.asarray(arr).dtype) == str(e.dtype).removeprefix("torch."), k
            assert np.array_equal(
                e.float().numpy(), np.asarray(arr, np.float32)
            ), k


class TestParamDtypePolicy:
    def test_bf16_storage_f32_init(self):
        # The standard TPU policy: init statistics computed at recorded
        # (f32) precision, storage in bf16, cast fused into the compiled
        # init program.  Values must equal the f32 materialization cast
        # after the fact; integer buffers must be untouched.
        import jax.numpy as jnp
        import numpy as np
        import torch

        from torchdistx_tpu.deferred_init import deferred_init
        from torchdistx_tpu.jax_bridge import materialize_module_jax

        class M(torch.nn.Module):
            def __init__(self):
                super().__init__()
                self.lin = torch.nn.Linear(8, 4)
                self.register_buffer("steps", torch.zeros(1, dtype=torch.int64))
                # float BUFFER (RoPE inv_freq / batchnorm stats stand-in):
                # must stay f32 under a bf16 param policy.
                self.register_buffer("inv_freq", torch.ones(3) / 7.0)

        m = deferred_init(M)
        full = materialize_module_jax(m, seed=0)
        half = materialize_module_jax(m, seed=0, param_dtype=jnp.bfloat16)
        assert str(half["lin.weight"].dtype) == "bfloat16"
        assert str(half["steps"].dtype).startswith("int")
        assert str(half["inv_freq"].dtype) == "float32"
        assert np.array_equal(
            np.asarray(full["lin.weight"].astype(jnp.bfloat16), np.float32),
            np.asarray(half["lin.weight"], np.float32),
        )

    def test_sharded_bf16_via_hf_wrapper(self):
        import jax.numpy as jnp
        from transformers import GPT2Config

        from torchdistx_tpu.hf import deferred_init_from_config, materialize_sharded
        from torchdistx_tpu.parallel import make_mesh

        m = deferred_init_from_config(
            GPT2Config(n_layer=2, n_embd=64, n_head=2, vocab_size=256)
        )
        mesh = make_mesh({"fsdp": 4, "tp": 2})
        params = materialize_sharded(
            m, mesh, seed=0, min_shard_size=1024, param_dtype=jnp.bfloat16
        )
        w = params["transformer.wte.weight"]
        assert str(w.dtype) == "bfloat16"
        assert not w.sharding.is_fully_replicated


class TestTorchNnInitSurface:
    """Every public torch.nn.init initializer records and lowers: the
    reference's whole value prop is that arbitrary module __init__ code
    replays (docs/src/deferred_init.rst); the bridge must keep up."""

    CASES = {
        "uniform": lambda w: torch.nn.init.uniform_(w, -1, 1),
        "normal": lambda w: torch.nn.init.normal_(w),
        "trunc_normal": lambda w: torch.nn.init.trunc_normal_(w),
        "constant": lambda w: torch.nn.init.constant_(w, 0.25),
        "ones": lambda w: torch.nn.init.ones_(w),
        "zeros": lambda w: torch.nn.init.zeros_(w),
        "xavier_uniform": lambda w: torch.nn.init.xavier_uniform_(w),
        "xavier_normal": lambda w: torch.nn.init.xavier_normal_(w),
        "kaiming_uniform": lambda w: torch.nn.init.kaiming_uniform_(w),
        "kaiming_normal": lambda w: torch.nn.init.kaiming_normal_(w),
        "orthogonal": lambda w: torch.nn.init.orthogonal_(w),
        "sparse": lambda w: torch.nn.init.sparse_(w, sparsity=0.5),
        "eye": lambda w: torch.nn.init.eye_(w),
    }

    @pytest.mark.parametrize("name", sorted(CASES))
    def test_records_and_lowers(self, name):
        import numpy as np

        from torchdistx_tpu import _graph
        from torchdistx_tpu.deferred_init import deferred_init
        from torchdistx_tpu.fake import is_fake
        from torchdistx_tpu.jax_bridge import materialize_params_jax

        fn = self.CASES[name]

        def build():
            w = torch.empty(8, 8)
            fn(w)
            return (w,)

        # torch replay: bitwise parity with eager under a fixed seed
        torch.manual_seed(3)
        eager = build()[0]
        torch.manual_seed(3)
        fakes = deferred_init(build)
        assert is_fake(fakes[0])
        real = _graph.materialize(fakes[0], retain_context=True)
        assert torch.equal(eager, real), name

        # jax bridge: lowers and produces structurally valid values
        w = np.asarray(materialize_params_jax({"w": fakes[0]}, seed=0)["w"])
        assert w.shape == (8, 8) and np.isfinite(w).all()
        if name == "eye":
            assert np.array_equal(w, np.eye(8, dtype=np.float32))
        elif name == "orthogonal":
            assert np.abs(w @ w.T - np.eye(8)).max() < 1e-5
        elif name == "sparse":
            assert ((w == 0).sum(axis=0) >= 4).all()
        elif name in ("constant", "ones", "zeros"):
            assert np.array_equal(w, eager.numpy())

    def test_dirac(self):
        import numpy as np

        from torchdistx_tpu.deferred_init import deferred_init
        from torchdistx_tpu.jax_bridge import materialize_params_jax

        def build():
            w = torch.empty(4, 4, 3)
            torch.nn.init.dirac_(w)
            return (w,)

        eager = build()[0]
        fakes = deferred_init(build)
        w = np.asarray(materialize_params_jax({"w": fakes[0]}, seed=0)["w"])
        assert np.array_equal(w, eager.numpy())


class TestParametrizationWrappers:
    """torch.nn.utils weight_norm / spectral_norm construct extra
    parameters with norm/clamp_min ops at init time; the recording must
    lower (reductions are allclose vs torch, not bitwise: summation
    order differs between backends)."""

    def test_weight_norm(self):
        import numpy as np

        from torchdistx_tpu.deferred_init import deferred_init
        from torchdistx_tpu.jax_bridge import materialize_module_jax

        m = deferred_init(lambda: torch.nn.utils.weight_norm(torch.nn.Linear(8, 8)))
        p = materialize_module_jax(m, seed=0)
        g = np.asarray(p["weight_g"])
        v = np.asarray(p["weight_v"])
        # weight_g is the row-norm of weight_v at init
        assert np.allclose(g[:, 0], np.sqrt((v * v).sum(axis=1)), rtol=1e-6)

    def test_spectral_norm(self):
        import numpy as np

        from torchdistx_tpu.deferred_init import deferred_init
        from torchdistx_tpu.jax_bridge import materialize_module_jax

        m = deferred_init(lambda: torch.nn.utils.spectral_norm(torch.nn.Linear(8, 8)))
        p = materialize_module_jax(m, seed=0)
        u = np.asarray(p["weight_u"])
        assert abs(np.linalg.norm(u) - 1.0) < 1e-5  # power-iteration vector is unit
        assert {"weight_orig", "weight_u", "weight_v", "bias"} <= set(p)
