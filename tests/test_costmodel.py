"""XLA device accounting (torchdistx_tpu.observe.costmodel): compiler
cost/memory probes, the link-bandwidth probe, cost attachment to
``jax.compile`` spans / run stats / the registry manifest, the
``tdx.jax.link_utilization`` and HBM high-water gauges, and the
compiler-derived MFU provenance in StepMeter and the train loop."""

from __future__ import annotations

import glob
import json
import os

import pytest

import torchdistx_tpu.config as tdx_config
from torchdistx_tpu import observe
from torchdistx_tpu.observe import costmodel


@pytest.fixture()
def telemetry():
    observe.reset()
    observe.enable(True)
    try:
        yield observe
    finally:
        observe.enable(None)
        observe.reset()


class TestProgramCosts:
    def test_costs_of_tiny_program(self):
        import jax
        import jax.numpy as jnp

        compiled = jax.jit(
            lambda a: (a @ a).sum()
        ).lower(jnp.ones((32, 32), jnp.float32)).compile()
        costs = costmodel.program_costs(compiled)
        assert costs is not None
        # 32³ MACs × 2 ≈ 65k flops, plus the reduction.
        assert costs["flops"] >= 2 * 32 * 32 * 32
        assert costs["argument_bytes"] == 32 * 32 * 4
        assert costs["peak_bytes"] > 0

    def test_probe_failure_degrades_to_none(self):
        class Broken:
            def cost_analysis(self):
                raise RuntimeError("no")

            def memory_analysis(self):
                raise AttributeError("no")

        assert costmodel.program_costs(Broken()) is None

    def test_list_and_dict_analysis_shapes(self):
        class ListShape:
            def cost_analysis(self):
                return [{"flops": 10.0, "bytes accessed": 4.0}]

            def memory_analysis(self):
                return None

        costs = costmodel.program_costs(ListShape())
        assert costs == {"flops": 10.0, "bytes_accessed": 4.0}

    def test_mfu_helper(self):
        assert costmodel.mfu(1e12, 1.0, 100.0) == pytest.approx(0.01)
        assert costmodel.mfu(0, 1.0, 100.0) is None
        assert costmodel.mfu(1e12, 1.0, None) is None


class TestLinkProbe:
    def test_measures_and_caches(self):
        costmodel.reset_link_probe()
        bw = costmodel.link_bandwidth_gbps(probe_mb=4)
        assert bw is not None and bw > 0
        assert costmodel.link_bandwidth_gbps() == bw  # cached

    def test_hbm_high_water_is_monotone(self, telemetry):
        costmodel.reset_link_probe()
        costmodel.note_program_memory({"peak_bytes": 100.0})
        costmodel.note_program_memory({"peak_bytes": 50.0})
        snap = {r["name"]: r["value"] for r in observe.counters().snapshot()}
        assert snap["tdx.jax.hbm_high_water_bytes"] == 100.0
        costmodel.note_program_memory({"peak_bytes": 300.0})
        snap = {r["name"]: r["value"] for r in observe.counters().snapshot()}
        assert snap["tdx.jax.hbm_high_water_bytes"] == 300.0


class TestMaterializeAccounting:
    def test_spans_stats_and_gauges(self, telemetry):
        import torch

        from torchdistx_tpu.deferred_init import deferred_init
        from torchdistx_tpu.jax_bridge import materialize_module_jax
        from torchdistx_tpu.jax_bridge import materialize as mat

        # Warm the link probe first: inside a span/timed region the
        # engine reads it cached-only (probing there would skew the
        # numbers it contextualizes).
        assert costmodel.link_bandwidth_gbps() > 0
        params = materialize_module_jax(deferred_init(torch.nn.Linear, 16, 8))
        assert set(params) == {"weight", "bias"}
        stats = mat.last_run_stats()
        assert stats.get("xla_flops", 0) > 0
        assert stats.get("xla_peak_bytes", 0) > 0
        (csp,) = [e for e in observe.tracer().events
                  if e["ph"] == "X" and e["name"] == "jax.compile"]
        assert csp["args"]["xla_flops"] > 0
        assert csp["args"]["xla_peak_bytes"] > 0
        snap = {r["name"]: r.get("value") for r in observe.counters().snapshot()}
        assert snap.get("tdx.jax.link_bandwidth_gbps", 0) > 0
        assert 0 < snap.get("tdx.jax.link_utilization", 0)
        assert snap.get("tdx.jax.hbm_high_water_bytes", 0) > 0
        (msp,) = [e for e in observe.tracer().events
                  if e["ph"] == "X" and e["name"] == "jax.materialize"]
        assert msp["args"]["link_utilization"] > 0

    def test_registry_manifest_carries_costs(self, telemetry, tmp_path,
                                             monkeypatch):
        import torch

        from torchdistx_tpu.deferred_init import deferred_init
        from torchdistx_tpu.jax_bridge import materialize_module_jax
        from torchdistx_tpu.jax_bridge import materialize as mat

        monkeypatch.setenv("TDX_CACHE_MIN_COMPILE_S", "0")
        mat._reset_cache_binding()
        cache = tmp_path / "cache"
        reg = tmp_path / "registry"
        try:
            with tdx_config.override(cache_dir=str(cache),
                                     registry_dir=str(reg)):
                materialize_module_jax(deferred_init(torch.nn.Linear, 16, 8))
        finally:
            mat._reset_cache_binding()
        metas = glob.glob(str(reg / "*" / "meta.json"))
        assert metas, list(reg.iterdir())
        doc = json.load(open(metas[0]))
        assert doc["xla_costs"]["flops"] > 0
        assert doc["xla_costs"]["peak_bytes"] > 0


class TestMfuProvenance:
    def test_stepmeter_gauge_name_declares_source(self, telemetry):
        m = observe.StepMeter(flops_per_step=1e9, peak_tflops=100.0,
                              flops_source="xla")
        m.start()
        m.stop()
        snap = {r["name"] for r in observe.counters().snapshot()}
        assert "tdx.train.mfu" in snap
        assert "tdx.train.mfu_est" not in snap
        observe.reset()
        m2 = observe.StepMeter(flops_per_step=1e9, peak_tflops=100.0)
        m2.start()
        m2.stop()
        snap = {r["name"] for r in observe.counters().snapshot()}
        assert "tdx.train.mfu_est" in snap
        assert "tdx.train.mfu" not in snap

    def test_downgrade_poisons_stale_measured_gauge(self, telemetry):
        import math

        m = observe.StepMeter(flops_per_step=1e9, peak_tflops=100.0,
                              flops_source="xla")
        m.start()
        m.stop()
        g = {r["name"]: r["value"] for r in observe.counters().snapshot()}
        assert g["tdx.train.mfu"] > 0
        # Mid-run provenance downgrade (the AOT fallback): the measured
        # gauge must not keep exporting its last value as if live.
        m.flops_source = "estimate"
        m.start()
        m.stop()
        g = {r["name"]: r["value"] for r in observe.counters().snapshot()}
        assert math.isnan(g["tdx.train.mfu"])
        assert g["tdx.train.mfu_est"] > 0

    def test_train_step_uses_compiler_flops(self, telemetry):
        import numpy as np
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh

        from torchdistx_tpu.models import make_llama
        from torchdistx_tpu.models.configs import TransformerConfig
        from torchdistx_tpu.parallel.train import make_train_step

        cfg = TransformerConfig(
            vocab_size=64, d_model=32, n_layers=1, n_heads=2, d_ff=64,
            max_seq_len=16, dtype=jnp.float32,
        )
        model = make_llama(cfg)
        mesh = Mesh(np.asarray(jax.devices("cpu")[:1]), ("dp",))
        tokens = jax.random.randint(jax.random.PRNGKey(0), (2, 16), 0, 64)
        params = jax.jit(model.init)(jax.random.PRNGKey(1), tokens)
        init_state, train_step, shard_batch = make_train_step(model, cfg, mesh)
        state = init_state(params)
        batch = shard_batch(tokens)
        for _ in range(2):
            state, _metrics = train_step(state, batch)
        steps = [e for e in observe.tracer().events
                 if e["ph"] == "X" and e["name"] == "train.step"]
        assert len(steps) == 2
        # Compiler FLOPs flowed through (tflops attr present on every
        # step, and the step program's footprint fed the high-water
        # gauge).  On CPU there is no peak table → no mfu gauge, which
        # is the "omit, never guess" contract.
        assert all(e["args"].get("tflops", 0) > 0 for e in steps)
        snap = {r["name"]: r.get("value") for r in observe.counters().snapshot()}
        assert snap.get("tdx.train.tflops", 0) > 0
        assert snap.get("tdx.jax.hbm_high_water_bytes", 0) > 0
