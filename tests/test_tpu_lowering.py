"""AOT TPU cross-lowering guards for the pallas kernels.

The CPU suite runs the kernels in interpret mode, which skips the
Pallas→Mosaic lowering entirely — that is how round 1 shipped an lse
BlockSpec that real TPUs reject (ADVICE r1).  ``jax.export`` with
``platforms=['tpu']`` runs the full Mosaic module generation (BlockSpec
tiling rules, layout checks, kernel jaxpr lowering) on a CPU-only host,
so every kernel flavor gets its TPU lowering exercised in CI even though
no chip is present.  (The final Mosaic→binary compile still only happens
on hardware; the bench phases cover that.)
"""

import jax
import jax.numpy as jnp
import pytest

from torchdistx_tpu.ops import flash_attention

B, S, H, D = 2, 512, 8, 64


def _export(fn, *args):
    return jax.export.export(jax.jit(fn), platforms=["tpu"])(*args)


def _inputs(kv_heads=H):
    q = jnp.zeros((B, S, H, D), jnp.bfloat16)
    k = jnp.zeros((B, S, kv_heads, D), jnp.bfloat16)
    v = jnp.zeros((B, S, kv_heads, D), jnp.bfloat16)
    return q, k, v


@pytest.mark.parametrize("kv_heads", [H, 2])
def test_flash_fwd_bwd_lowers_for_tpu(kv_heads):
    q, k, v = _inputs(kv_heads)

    def fwd_and_grads(q, k, v):
        out = flash_attention(
            q, k, v, causal=True, block_q=256, block_k=256, interpret=False
        )
        grads = jax.grad(
            lambda q, k, v: flash_attention(
                q, k, v, causal=True, block_q=256, block_k=256, interpret=False
            ).astype(jnp.float32).sum(),
            argnums=(0, 1, 2),
        )(q, k, v)
        return out, grads

    assert _export(fwd_and_grads, q, k, v).mlir_module()


def test_bench_shape_lowers_for_tpu():
    # The production bench configuration (B=4, H=16, S=2048, D=64,
    # blocks 1024x1024, bf16, causal) — exactly what phase_flash compiles
    # on the chip.
    q = jnp.zeros((4, 2048, 16, 64), jnp.bfloat16)

    def fwd(q, k, v):
        return flash_attention(
            q, k, v, causal=True, block_q=1024, block_k=1024, interpret=False
        )

    assert _export(fwd, q, q, q).mlir_module()


def test_graft_entry_shape_lowers_for_tpu():
    # The driver's single-chip compile check runs the flagship TINY
    # Llama THROUGH the flash kernel (__graft_entry__.entry): guard its
    # exact shape class — f32, D=16, S=32, GQA 4/2, blocks clamped to
    # 32x32 — so a tiling assumption valid only at D=64 cannot pass CI
    # and then fail the driver's on-hardware Mosaic compile.
    q = jnp.zeros((2, 32, 4, 16), jnp.float32)
    k = jnp.zeros((2, 32, 2, 16), jnp.float32)

    def fwd(q, k, v):
        return flash_attention(q, k, v, causal=True, interpret=False)

    assert _export(fwd, q, k, k).mlir_module()


@pytest.mark.parametrize("bias_heads", [H, 1])
def test_flash_bias_and_segments_lower_for_tpu(bias_heads):
    # The full operand surface in one program: additive bias (incl. the
    # dbias kernel and its head-broadcast grid) + packed segment ids
    # (incl. the _seg_mask transpose) through fwd and every backward
    # kernel.
    q, k, v = _inputs()
    bias = jnp.zeros((bias_heads, S, S), jnp.float32)
    seg = jnp.zeros((B, S), jnp.int32)

    def fwd_and_grads(q, k, v, bias, seg):
        kw = dict(
            causal=True, segment_ids=seg, block_q=256, block_k=256,
            interpret=False,
        )
        out = flash_attention(q, k, v, bias=bias, **kw)
        grads = jax.grad(
            lambda q, k, v, b: flash_attention(q, k, v, bias=b, **kw)
            .astype(jnp.float32)
            .sum(),
            argnums=(0, 1, 2, 3),
        )(q, k, v, bias)
        return out, grads

    assert _export(fwd_and_grads, q, k, v, bias, seg).mlir_module()
