"""Tests for the parallelism layer: ring attention, pipeline, train step.

The reference has no distributed layer to test (SURVEY.md §4: "No
distributed tests, fixtures, mocks, or fake backends exist"); this suite
runs everything on the 8-device virtual CPU mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torchdistx_tpu.abstract import deferred_init, materialize
from torchdistx_tpu.models import (
    TINY,
    TINY_MOE,
    decoder_lm_plan,
    make_llama,
    make_mixtral,
)
from torchdistx_tpu.models.layers import default_attention
from torchdistx_tpu.parallel import make_mesh
from torchdistx_tpu.parallel.pipeline import pipelined_decoder_apply
from torchdistx_tpu.parallel.ring_attention import make_ring_attention
from torchdistx_tpu.parallel.train import make_train_step
from torchdistx_tpu.parallel.ulysses import make_ulysses_attention


class TestRingAttention:
    @pytest.fixture(scope="class")
    def mesh(self):
        return make_mesh({"dp": 2, "sp": 4})

    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_reference(self, mesh, causal):
        B, S, H, KV, D = 2, 32, 4, 2, 16
        key = jax.random.PRNGKey(0)
        q = jax.random.normal(key, (B, S, H, D))
        k = jax.random.normal(jax.random.fold_in(key, 1), (B, S, KV, D))
        v = jax.random.normal(jax.random.fold_in(key, 2), (B, S, KV, D))
        ring = make_ring_attention(mesh)
        ref = default_attention(q, k, v, causal=causal)
        out = jax.jit(lambda q, k, v: ring(q, k, v, causal=causal))(q, k, v)
        assert float(jnp.abs(ref - out).max()) < 1e-5

    def test_gradients_flow(self, mesh):
        B, S, H, D = 2, 16, 4, 8
        q = jax.random.normal(jax.random.PRNGKey(0), (B, S, H, D))
        k = jax.random.normal(jax.random.PRNGKey(1), (B, S, H, D))
        v = jax.random.normal(jax.random.PRNGKey(2), (B, S, H, D))
        ring = make_ring_attention(mesh)

        g = jax.jit(jax.grad(lambda q: (ring(q, k, v) ** 2).sum()))(q)
        assert bool(jnp.isfinite(g).all())
        assert float(jnp.abs(g).sum()) > 0

    @pytest.mark.parametrize("causal", [True, False])
    def test_bias_matches_reference(self, mesh, causal):
        # T5-style additive [H, S, S] bias, sharded over query rows and
        # block-sliced per ring step (VERDICT r1 weak #6).
        B, S, H, D = 2, 32, 4, 16
        key = jax.random.PRNGKey(0)
        q = jax.random.normal(key, (B, S, H, D))
        k = jax.random.normal(jax.random.fold_in(key, 1), (B, S, H, D))
        v = jax.random.normal(jax.random.fold_in(key, 2), (B, S, H, D))
        bias = jax.random.normal(jax.random.fold_in(key, 3), (H, S, S))
        ring = make_ring_attention(mesh)
        ref = default_attention(q, k, v, causal=causal, bias=bias)
        out = jax.jit(
            lambda q, k, v, b: ring(q, k, v, causal=causal, bias=b)
        )(q, k, v, bias)
        assert float(jnp.abs(ref - out).max()) < 1e-5

    @pytest.mark.parametrize("causal", [True, False])
    def test_cross_attention_lengths(self, mesh, causal):
        # Key/value sequence differs from the query sequence (both sharded
        # over sp); the causal variant must keep the oracle's bottom-right
        # alignment (tril k=T-S).
        B, Sq, Sk, H, D = 2, 16, 32, 4, 8
        q = jax.random.normal(jax.random.PRNGKey(0), (B, Sq, H, D))
        k = jax.random.normal(jax.random.PRNGKey(1), (B, Sk, H, D))
        v = jax.random.normal(jax.random.PRNGKey(2), (B, Sk, H, D))
        ring = make_ring_attention(mesh)
        ref = default_attention(q, k, v, causal=causal)
        out = jax.jit(lambda q, k, v: ring(q, k, v, causal=causal))(q, k, v)
        assert float(jnp.abs(ref - out).max()) < 1e-5

    def test_model_with_ring_attention(self, mesh):
        cfg = TINY
        toks = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, cfg.vocab_size)
        plain = make_llama(cfg)
        params = plain.init(jax.random.PRNGKey(0), toks)
        ringed = make_llama(cfg, attn_fn=make_ring_attention(mesh))
        ref = plain.apply(params, toks)
        out = jax.jit(lambda p, t: ringed.apply(p, t))(params, toks)
        assert float(jnp.abs(ref - out).max()) < 2e-4


class TestUlyssesAttention:
    @pytest.fixture(scope="class")
    def mesh(self):
        return make_mesh({"dp": 2, "sp": 4})

    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_reference(self, mesh, causal):
        B, S, H, KV, D = 2, 32, 8, 4, 16  # KV=4 == sp size: kv heads split
        key = jax.random.PRNGKey(0)
        q = jax.random.normal(key, (B, S, H, D))
        k = jax.random.normal(jax.random.fold_in(key, 1), (B, S, KV, D))
        v = jax.random.normal(jax.random.fold_in(key, 2), (B, S, KV, D))
        uly = make_ulysses_attention(mesh)
        ref = default_attention(q, k, v, causal=causal)
        out = jax.jit(lambda q, k, v: uly(q, k, v, causal=causal))(q, k, v)
        assert float(jnp.abs(ref - out).max()) < 1e-5

    @pytest.mark.parametrize("kv_heads", [2, 1])
    def test_gqa_grouped_slots(self, mesh, kv_heads):
        # KV < sp (GQA, and KV=1 true MQA): kv heads are repeated to one
        # SLOT per device (n slots), not to the H query heads, so each
        # device receives exactly the one kv head its query chunk reads.
        B, S, H, D = 2, 16, 8, 8
        q = jax.random.normal(jax.random.PRNGKey(0), (B, S, H, D))
        k = jax.random.normal(jax.random.PRNGKey(1), (B, S, kv_heads, D))
        v = jax.random.normal(jax.random.PRNGKey(2), (B, S, kv_heads, D))
        uly = make_ulysses_attention(mesh)
        ref = default_attention(q, k, v, causal=True)
        out = jax.jit(lambda q, k, v: uly(q, k, v, causal=True))(q, k, v)
        assert float(jnp.abs(ref - out).max()) < 1e-5

    def test_gqa_grouped_slots_move_fewer_bytes(self, mesh, monkeypatch):
        # VERDICT r2 weak #5: with KV < n the K/V all-to-alls must move
        # n slots per device, not H — assert the operand head dims seen
        # by the collective drop from H (old broadcast) to n.
        import torchdistx_tpu.parallel.ulysses as uly_mod

        B, S, H, KV, D = 2, 16, 8, 2, 8
        n = 4  # sp size in the fixture mesh
        shapes = []
        real = uly_mod.all_to_all

        def spy(x, axis_name, **kw):
            shapes.append(tuple(x.shape))
            return real(x, axis_name, **kw)

        monkeypatch.setattr(uly_mod, "all_to_all", spy)
        q = jax.random.normal(jax.random.PRNGKey(0), (B, S, H, D))
        k = jax.random.normal(jax.random.PRNGKey(1), (B, S, KV, D))
        v = jax.random.normal(jax.random.PRNGKey(2), (B, S, KV, D))
        uly = make_ulysses_attention(mesh)
        jax.jit(lambda q, k, v: uly(q, k, v, causal=True))(q, k, v)
        # Inbound all-to-alls (local seq s = S/n): q at H heads, k and v
        # at n slots each; the H-head broadcast would have sent H.
        inbound = [s for s in shapes if s[1] == S // n]
        assert sorted(s[2] for s in inbound) == sorted([H, n, n])
        assert all(s[2] != H for s in inbound[1:]), shapes

    @pytest.mark.parametrize("kv_heads,n_q", [(6, 24), (3, 24), (6, 36)])
    def test_gqa_ragged_gcd_grouping(self, mesh, kv_heads, n_q):
        # KV and n=4 divide neither way (VERDICT r3 weak #5): the gcd
        # grouping must still match the single-device GQA oracle, with
        # no broadcast warning (these cases all have H > lcm(n, KV)).
        B, S, D = 2, 16, 8
        q = jax.random.normal(jax.random.PRNGKey(0), (B, S, n_q, D))
        k = jax.random.normal(jax.random.PRNGKey(1), (B, S, kv_heads, D))
        v = jax.random.normal(jax.random.PRNGKey(2), (B, S, kv_heads, D))
        uly = make_ulysses_attention(mesh)
        ref = default_attention(q, k, v, causal=True)
        import warnings as _w

        with _w.catch_warnings():
            _w.simplefilter("error")  # any broadcast warning fails
            out = jax.jit(lambda q, k, v: uly(q, k, v, causal=True))(q, k, v)
        assert float(jnp.abs(ref - out).max()) < 1e-5

    def test_gqa_ragged_moves_fewer_bytes(self, mesh, monkeypatch):
        # Ragged KV=3 over n=4 with H=24 (g=1, kv'=3): the K/V
        # all-to-alls carry kv'*n=12 slots — 3 received per device —
        # where the old broadcast carried H=24 (6 per device).
        import torchdistx_tpu.parallel.ulysses as uly_mod

        B, S, H, KV, D = 2, 16, 24, 3, 8
        n = 4
        shapes = []
        real = uly_mod.all_to_all

        def spy(x, axis_name, **kw):
            shapes.append(tuple(x.shape))
            return real(x, axis_name, **kw)

        monkeypatch.setattr(uly_mod, "all_to_all", spy)
        q = jax.random.normal(jax.random.PRNGKey(0), (B, S, H, D))
        k = jax.random.normal(jax.random.PRNGKey(1), (B, S, KV, D))
        v = jax.random.normal(jax.random.PRNGKey(2), (B, S, KV, D))
        uly = make_ulysses_attention(mesh)
        ref = default_attention(q, k, v, causal=True)
        out = jax.jit(lambda q, k, v: uly(q, k, v, causal=True))(q, k, v)
        assert float(jnp.abs(ref - out).max()) < 1e-5
        inbound = [s for s in shapes if s[1] == S // n]
        assert sorted(s[2] for s in inbound) == sorted([H, KV * n, KV * n])

    def test_gqa_ragged_irreducible_warns(self, mesh):
        # H == lcm(n, KV): every slot feeds exactly one query head, so
        # the gcd grouping degenerates to the broadcast — the one case
        # the warning is still for.  Oracle results regardless.
        B, S, H, KV, D = 2, 16, 12, 6, 8  # lcm(4, 6) = 12 == H
        q = jax.random.normal(jax.random.PRNGKey(0), (B, S, H, D))
        k = jax.random.normal(jax.random.PRNGKey(1), (B, S, KV, D))
        v = jax.random.normal(jax.random.PRNGKey(2), (B, S, KV, D))
        uly = make_ulysses_attention(mesh)
        ref = default_attention(q, k, v, causal=True)
        with pytest.warns(UserWarning, match="divide neither way"):
            out = jax.jit(lambda q, k, v: uly(q, k, v, causal=True))(q, k, v)
        assert float(jnp.abs(ref - out).max()) < 1e-5

    def test_gradients_flow(self, mesh):
        B, S, H, D = 2, 16, 4, 8
        q = jax.random.normal(jax.random.PRNGKey(0), (B, S, H, D))
        k = jax.random.normal(jax.random.PRNGKey(1), (B, S, H, D))
        v = jax.random.normal(jax.random.PRNGKey(2), (B, S, H, D))
        uly = make_ulysses_attention(mesh)
        g = jax.jit(jax.grad(lambda q: (uly(q, k, v) ** 2).sum()))(q)
        assert bool(jnp.isfinite(g).all())
        assert float(jnp.abs(g).sum()) > 0

    @pytest.mark.parametrize("causal", [True, False])
    def test_bias_matches_reference(self, mesh, causal):
        # Bias heads ride the all-to-all layout: sharded head-wise, full
        # sequence extents local (VERDICT r1 weak #6).
        B, S, H, D = 2, 32, 8, 16
        key = jax.random.PRNGKey(0)
        q = jax.random.normal(key, (B, S, H, D))
        k = jax.random.normal(jax.random.fold_in(key, 1), (B, S, H, D))
        v = jax.random.normal(jax.random.fold_in(key, 2), (B, S, H, D))
        bias = jax.random.normal(jax.random.fold_in(key, 3), (H, S, S))
        uly = make_ulysses_attention(mesh)
        ref = default_attention(q, k, v, causal=causal, bias=bias)
        out = jax.jit(
            lambda q, k, v, b: uly(q, k, v, causal=causal, bias=b)
        )(q, k, v, bias)
        assert float(jnp.abs(ref - out).max()) < 1e-5

    def test_head_count_must_divide(self, mesh):
        uly = make_ulysses_attention(mesh)
        x = jnp.ones((1, 8, 6, 4))  # 6 heads, sp=4
        with pytest.raises(ValueError, match="divide query heads"):
            uly(x, x, x)

    def test_no_sp_axis_degrades(self):
        mesh = make_mesh({"dp": 8})
        uly = make_ulysses_attention(mesh)
        B, S, H, D = 1, 16, 4, 8
        q = jax.random.normal(jax.random.PRNGKey(0), (B, S, H, D))
        ref = default_attention(q, q, q, causal=True)
        assert float(jnp.abs(uly(q, q, q, causal=True) - ref).max()) < 1e-6

    def test_model_runs_with_ulysses(self, mesh):
        model = make_llama(TINY, attn_fn=make_ulysses_attention(mesh))
        toks = jnp.zeros((2, 32), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), toks)
        logits = jax.jit(model.apply)(params, toks)
        assert logits.shape == (2, 32, TINY.vocab_size)


class TestT5SequenceParallel:
    """BASELINE config 4's family on the long-context paths: the relative-
    position bias rides both strategies now (VERDICT r1 weak #6)."""

    @pytest.fixture(scope="class")
    def mesh(self):
        return make_mesh({"dp": 2, "sp": 4})

    @pytest.fixture(scope="class")
    def setup(self):
        from torchdistx_tpu.models import TINY_T5, make_t5

        cfg = TINY_T5
        enc = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, cfg.vocab_size)
        dec = jax.random.randint(jax.random.PRNGKey(2), (2, 32), 0, cfg.vocab_size)
        dense = make_t5(cfg)
        params = dense.init(jax.random.PRNGKey(0), enc, dec)
        ref = dense.apply(params, enc, dec)
        return cfg, enc, dec, params, ref

    def test_t5_with_ring_attention(self, mesh, setup):
        from torchdistx_tpu.models import make_t5

        cfg, enc, dec, params, ref = setup
        model = make_t5(cfg, attn_fn=make_ring_attention(mesh))
        out = jax.jit(lambda p, e, d: model.apply(p, e, d))(params, enc, dec)
        assert float(jnp.abs(ref - out).max()) < 2e-4

    def test_t5_with_ulysses_attention(self, mesh, setup):
        from torchdistx_tpu.models import make_t5

        cfg, enc, dec, params, ref = setup
        model = make_t5(cfg, attn_fn=make_ulysses_attention(mesh))
        out = jax.jit(lambda p, e, d: model.apply(p, e, d))(params, enc, dec)
        assert float(jnp.abs(ref - out).max()) < 2e-4

    def test_t5_with_ulysses_flash_inner(self, mesh, setup):
        # Ulysses re-shards heads and hands the pre-sharded [H/n, S, T]
        # bias to its inner attention — which can now be the bias-capable
        # flash kernels, composing all-to-all sp with blockwise compute.
        from torchdistx_tpu.models import make_t5
        from torchdistx_tpu.ops import make_flash_attention

        cfg, enc, dec, params, ref = setup
        model = make_t5(
            cfg,
            attn_fn=make_ulysses_attention(
                mesh, inner_attn=make_flash_attention(block_q=8, block_k=8)
            ),
        )
        out = jax.jit(lambda p, e, d: model.apply(p, e, d))(params, enc, dec)
        assert float(jnp.abs(ref - out).max()) < 2e-4

    def test_t5_with_ring_flash_attention(self, mesh, setup):
        # The bias path now runs the flash kernels per ring step (the
        # decoder's causal cross-attention transparently takes the dense
        # ring inside the same wrapper).
        from torchdistx_tpu.models import make_t5
        from torchdistx_tpu.parallel import make_ring_flash_attention

        cfg, enc, dec, params, ref = setup
        model = make_t5(cfg, attn_fn=make_ring_flash_attention(mesh, block_q=8, block_k=8))
        out = jax.jit(lambda p, e, d: model.apply(p, e, d))(params, enc, dec)
        assert float(jnp.abs(ref - out).max()) < 2e-4

        def loss(p):
            return (model.apply(p, enc, dec).astype(jnp.float32) ** 2).mean()

        grads = jax.jit(jax.grad(loss))(params)
        assert all(bool(jnp.isfinite(g).all()) for g in jax.tree.leaves(grads))


class TestPipeline:
    @pytest.fixture(scope="class")
    def mesh(self):
        return make_mesh({"pp": 2, "dp": 2, "tp": 2})

    def test_forward_matches_sequential(self, mesh):
        cfg = TINY
        m = make_llama(cfg)
        toks = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, cfg.vocab_size)
        params = m.init(jax.random.PRNGKey(0), toks)
        ref = m.apply(params, toks)
        out = jax.jit(
            lambda p, t: pipelined_decoder_apply(cfg, p, t, mesh, n_microbatches=4)
        )(params, toks)
        assert float(jnp.abs(ref - out).max()) < 1e-4

    def test_packed_segments_match_sequential(self, mesh):
        # Packed ids travel with their microbatch through the stage
        # rotation; per-microbatch masking must equal the dense model's.
        cfg = TINY
        m = make_llama(cfg)
        B, S = 8, 16
        toks = jax.random.randint(jax.random.PRNGKey(1), (B, S), 0, cfg.vocab_size)
        # Different packing per example so microbatches genuinely differ.
        seg = (jnp.arange(S)[None, :] >= jnp.arange(2, 2 + B)[:, None]).astype(
            jnp.int32
        )
        params = m.init(jax.random.PRNGKey(0), toks)
        ref = m.apply(params, toks, segment_ids=seg)
        out = jax.jit(
            lambda p, t, s: pipelined_decoder_apply(
                cfg, p, t, mesh, n_microbatches=4, segment_ids=s
            )
        )(params, toks, seg)
        assert float(jnp.abs(ref - out).max()) < 1e-4

        # The backward through the seg-aware schedule (the path the old
        # NotImplementedError in make_train_step used to block).
        init_state, step, shard_batch = make_train_step(
            m, cfg, mesh, pipeline=True, n_microbatches=4
        )
        state = init_state(params)
        state, metrics = step(state, shard_batch(toks), shard_batch(seg))
        assert np.isfinite(float(metrics["loss"]))
        assert np.isfinite(float(metrics["grad_norm"]))

    def test_gpt2_layout_via_decomposition(self, mesh):
        # Second param-tree layout (wte/wpe learned positions, tied head)
        # through the model-exported decomposition — no key probing
        # (VERDICT r1 weak #5).
        from torchdistx_tpu.models import TINY_GPT2, make_gpt2

        cfg = TINY_GPT2
        m = make_gpt2(cfg)
        toks = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, cfg.vocab_size)
        params = m.init(jax.random.PRNGKey(0), toks)
        ref = m.apply(params, toks)
        decomp = m.pipeline_decomposition()
        out = jax.jit(
            lambda p, t: pipelined_decoder_apply(
                cfg, p, t, mesh, decomp=decomp, n_microbatches=4
            )
        )(params, toks)
        assert float(jnp.abs(ref - out).max()) < 1e-4

    def test_untied_head_layout_via_decomposition(self, mesh):
        # Third layout variant: untied lm_head through the Llama export.
        cfg = TINY
        m = make_llama(cfg)
        toks = jax.random.randint(jax.random.PRNGKey(2), (8, 16), 0, cfg.vocab_size)
        params = m.init(jax.random.PRNGKey(0), toks)
        assert "lm_head" in params["params"]
        ref = m.apply(params, toks)
        out = jax.jit(
            lambda p, t: pipelined_decoder_apply(
                cfg, p, t, mesh, decomp=m.pipeline_decomposition(), n_microbatches=4
            )
        )(params, toks)
        assert float(jnp.abs(ref - out).max()) < 1e-4

    def test_moe_aux_matches_microbatched_reference(self, mesh):
        # pp x ep: the router load-balancing aux must ride the schedule
        # (VERDICT r2 weak #2 — it used to be silently dropped).  The
        # exact oracle is the microbatched non-pipelined forward: aux is
        # quadratic in the routing distribution, so the schedule-wide
        # value is the MEAN over per-microbatch values (the same
        # semantics as any gradient-accumulating trainer), not the
        # full-batch value.
        from torchdistx_tpu.parallel.pipeline import _sum_aux

        cfg = TINY_MOE
        moe_mesh = make_mesh({"pp": 2, "ep": 2, "dp": 2})
        m = make_mixtral(cfg)
        B, S, n_mb = 8, 16, 4
        toks = jax.random.randint(jax.random.PRNGKey(1), (B, S), 0, cfg.vocab_size)
        params = m.init(jax.random.PRNGKey(0), toks)

        _, aux = jax.jit(
            lambda p, t: pipelined_decoder_apply(
                cfg, p, t, moe_mesh, n_microbatches=n_mb, return_aux=True
            )
        )(params, toks)

        aux_ref = 0.0
        for i in range(n_mb):
            mb = toks[i * (B // n_mb) : (i + 1) * (B // n_mb)]
            _, mvars = m.apply(params, mb, mutable=["losses"])
            aux_ref += float(_sum_aux(mvars.get("losses", {})))
        aux_ref /= n_mb

        # Regression: flax nn.scan traces the body twice; the default
        # tuple-append sow recorded the aux TWICE (2x the intended
        # router_aux_weight in every dense MoE step).  Overwrite-sow
        # must leave exactly one stacked leaf.
        leaves = jax.tree.leaves(mvars.get("losses", {}))
        assert len(leaves) == 1 and leaves[0].shape == (cfg.n_layers,)

        assert float(aux) > 0.0
        np.testing.assert_allclose(float(aux), aux_ref, rtol=1e-4)

        # And through make_train_step: metrics must report the real aux.
        init_state, step, shard_batch = make_train_step(
            m, cfg, moe_mesh, pipeline=True, n_microbatches=n_mb,
            batch_axes=("dp",),
        )
        state = init_state(params)
        _, metrics = step(state, shard_batch(toks))
        np.testing.assert_allclose(float(metrics["aux"]), aux_ref, rtol=1e-3)

    @pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
    def test_moe_aux_accumulates_across_steps(self, schedule):
        # VERDICT r3 weak #7: aux (and the optimizer it feeds) was only
        # ever checked at step 1.  Run a 4-step AdamW trajectory on a
        # pp x ep mesh and assert BOTH the loss and the aux match the
        # unpipelined single-device trajectory step for step — state
        # updates compound, so a schedule bug in aux accumulation or
        # gradient flow diverges the tail even if step 1 agrees.
        cfg = TINY_MOE
        m = make_mixtral(cfg)
        B, S, n_mb, n_steps = 8, 16, 4, 4
        toks_steps = [
            jax.random.randint(jax.random.PRNGKey(10 + i), (B, S), 0, cfg.vocab_size)
            for i in range(n_steps)
        ]
        params = m.init(jax.random.PRNGKey(0), toks_steps[0])

        def trajectory(mesh, **kw):
            init_state, step, shard_batch = make_train_step(m, cfg, mesh, **kw)
            state = init_state(jax.device_get(params))
            out = []
            for toks in toks_steps:
                state, metrics = step(state, shard_batch(toks))
                out.append((float(metrics["loss"]), float(metrics["aux"])))
            return out

        moe_mesh = make_mesh({"pp": 2, "ep": 2, "dp": 2})
        got = trajectory(
            moe_mesh, pipeline=True, pipeline_schedule=schedule,
            n_microbatches=n_mb, batch_axes=("dp",),
        )
        # Single-device reference with pp=1: a one-stage pipeline keeps
        # the microbatched grad-accumulation semantics (aux/loss are
        # means over microbatches) while removing every cross-device
        # concern from the oracle.
        ref_mesh = make_mesh({"pp": 1, "dp": 1}, devices=jax.devices()[:1])
        ref = trajectory(
            ref_mesh, pipeline=True, n_microbatches=n_mb, batch_axes=("dp",),
        )
        for k, ((gl, ga), (rl, ra)) in enumerate(zip(got, ref)):
            assert abs(gl - rl) <= 2e-3, f"step {k} loss: {gl} vs {rl}"
            assert abs(ga - ra) <= 2e-4 * max(1.0, abs(ra)), (
                f"step {k} aux: {ga} vs {ra}"
            )

    def test_grad_matches_sequential(self, mesh):
        cfg = TINY
        m = make_llama(cfg)
        toks = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, cfg.vocab_size)
        params = m.init(jax.random.PRNGKey(0), toks)

        g = jax.jit(
            jax.grad(
                lambda p: (
                    pipelined_decoder_apply(cfg, p, toks, mesh, n_microbatches=4) ** 2
                ).mean()
            )
        )(params)
        gref = jax.grad(lambda p: (m.apply(p, toks) ** 2).mean())(params)
        diffs = jax.tree.map(lambda a, b: float(jnp.abs(a - b).max()), g, gref)
        assert max(jax.tree.leaves(diffs)) < 1e-5

    def test_1f1b_grads_match_dense(self, mesh):
        # The fused fwd+bwd 1F1B schedule produces gradients WITHOUT
        # jax.grad over the schedule — they must still equal the dense
        # model's (VERDICT r2 weak #3).
        from torchdistx_tpu.parallel.pipeline import pipeline_train_1f1b
        from torchdistx_tpu.parallel.train import lm_cross_entropy

        cfg = TINY
        m = make_llama(cfg)
        toks = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, cfg.vocab_size)
        params = m.init(jax.random.PRNGKey(0), toks)
        metrics, grads = jax.jit(
            lambda p, t: pipeline_train_1f1b(
                cfg, p, t, mesh, decomp=m.pipeline_decomposition(),
                n_microbatches=4,
            )
        )(params, toks)
        lref, gref = jax.value_and_grad(
            lambda p: lm_cross_entropy(m.apply(p, toks), toks)
        )(params)
        np.testing.assert_allclose(float(metrics["loss"]), float(lref), rtol=1e-6)
        diffs = jax.tree.map(
            lambda a, b: float(jnp.abs(a - b).max()), grads["params"], gref["params"]
        )
        assert max(jax.tree.leaves(diffs)) < 1e-5

    def test_1f1b_gpt2_tied_head_grads_match_dense(self):
        # GPT-2 layout: learned positions in embed, TIED head — the case
        # where 1F1B's manual head-vjp + embed-vjp summation must
        # reproduce the total derivative of the shared wte table.
        from torchdistx_tpu.models import TINY_GPT2, make_gpt2
        from torchdistx_tpu.parallel.pipeline import pipeline_train_1f1b
        from torchdistx_tpu.parallel.train import lm_cross_entropy

        cfg = TINY_GPT2
        g_mesh = make_mesh({"pp": 2, "dp": 4})
        m = make_gpt2(cfg)
        toks = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, cfg.vocab_size)
        params = m.init(jax.random.PRNGKey(0), toks)
        metrics, grads = jax.jit(
            lambda p, t: pipeline_train_1f1b(
                cfg, p, t, g_mesh, decomp=m.pipeline_decomposition(),
                n_microbatches=4,
            )
        )(params, toks)
        lref, gref = jax.value_and_grad(
            lambda p: lm_cross_entropy(m.apply(p, toks), toks)
        )(params)
        np.testing.assert_allclose(float(metrics["loss"]), float(lref), rtol=1e-6)
        diffs = jax.tree.map(
            lambda a, b: float(jnp.abs(a - b).max()), grads["params"], gref["params"]
        )
        assert max(jax.tree.leaves(diffs)) < 1e-5

    def test_1f1b_moe_packed_matches_microbatched(self):
        # MoE aux + packed segments through 1F1B: loss and grads equal
        # the microbatched dense oracle (sum-form CE over the global
        # valid count + microbatch-averaged aux).
        from torchdistx_tpu.parallel.pipeline import (
            _sum_aux,
            pipeline_train_1f1b,
        )

        cfg = TINY_MOE
        moe_mesh = make_mesh({"pp": 2, "ep": 2, "dp": 2})
        m = make_mixtral(cfg)
        B, S, n_mb = 8, 16, 4
        toks = jax.random.randint(jax.random.PRNGKey(1), (B, S), 0, cfg.vocab_size)
        seg = (jnp.arange(S)[None, :] >= jnp.arange(2, 2 + B)[:, None]).astype(
            jnp.int32
        )
        params = m.init(jax.random.PRNGKey(0), toks)
        metrics, grads = jax.jit(
            lambda p, t, s: pipeline_train_1f1b(
                cfg, p, t, moe_mesh, decomp=m.pipeline_decomposition(),
                n_microbatches=n_mb, segment_ids=s,
            )
        )(params, toks, seg)

        def dense(p):
            ce_tot, aux_tot = 0.0, 0.0
            for i in range(n_mb):
                sl = slice(i * (B // n_mb), (i + 1) * (B // n_mb))
                logits, mv = m.apply(
                    p, toks[sl], segment_ids=seg[sl], mutable=["losses"]
                )
                aux_tot = aux_tot + _sum_aux(mv.get("losses", {}))
                lp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32))
                ll = jnp.take_along_axis(
                    lp, toks[sl][:, 1:][..., None], -1
                )[..., 0]
                valid = jnp.logical_and(
                    seg[sl][:, :-1] == seg[sl][:, 1:], seg[sl][:, 1:] >= 0
                )
                ce_tot = ce_tot - jnp.sum(ll * valid)
            valid_all = jnp.logical_and(seg[:, :-1] == seg[:, 1:], seg[:, 1:] >= 0)
            return ce_tot / jnp.sum(valid_all) + aux_tot / n_mb

        lref, gref = jax.value_and_grad(dense)(params)
        np.testing.assert_allclose(float(metrics["loss"]), float(lref), rtol=1e-5)
        assert float(metrics["aux"]) > 0.0
        diffs = jax.tree.map(
            lambda a, b: float(jnp.abs(a - b).max()), grads["params"], gref["params"]
        )
        assert max(jax.tree.leaves(diffs)) < 1e-5

    def test_1f1b_uses_less_temp_memory_than_gpipe(self):
        # The point of 1F1B: bounded in-flight state (stage-input stash +
        # recompute) instead of every microbatch's layer activations.
        # Compare XLA's compiled temp allocation for the two schedules.
        from torchdistx_tpu.abstract import deferred_init, materialize
        from torchdistx_tpu.models import decoder_lm_plan
        from torchdistx_tpu.parallel.pipeline import pipeline_plan_overrides
        from torchdistx_tpu.parallel.sharding import ShardingPlan

        cfg = TINY.replace(n_layers=4)
        mem_mesh = make_mesh({"pp": 4, "dp": 2})
        m = make_llama(cfg)
        B, S, n_mb = 16, 64, 16
        toks = jax.random.randint(jax.random.PRNGKey(1), (B, S), 0, cfg.vocab_size)
        fakes = deferred_init(m.init, jax.random.PRNGKey(0), toks)
        base = decoder_lm_plan(fsdp=None, ep=None, tp=None)
        plan = ShardingPlan(
            pipeline_plan_overrides() + [(p.pattern, s) for p, s in base.rules]
        )
        params = materialize(fakes, mesh=mem_mesh, plan=plan)

        temps, losses = {}, {}
        for sched in ("gpipe", "1f1b"):
            init_state, step, shard_batch = make_train_step(
                m, cfg, mem_mesh, pipeline=True, n_microbatches=n_mb,
                pipeline_schedule=sched, batch_axes=("dp",), donate=False,
            )
            state = init_state(params)
            comp = step.lower(state, shard_batch(toks)).compile()
            ma = comp.memory_analysis()
            if ma is None or not hasattr(ma, "temp_size_in_bytes"):
                pytest.skip("backend exposes no memory analysis")
            temps[sched] = ma.temp_size_in_bytes
            _, metrics = step(state, shard_batch(toks))
            losses[sched] = float(metrics["loss"])
        np.testing.assert_allclose(losses["gpipe"], losses["1f1b"], rtol=1e-5)
        # Observed ~8x on this config; assert a conservative margin.
        assert temps["1f1b"] < temps["gpipe"] / 2, temps


class TestTrainStep:
    def _run(self, cfg, make_model, mesh_axes, n_steps=3, **step_kw):
        mesh = make_mesh(mesh_axes)
        model = make_model(cfg)
        toks = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0, cfg.vocab_size)
        fakes = deferred_init(model.init, jax.random.PRNGKey(0), toks)
        params = materialize(fakes, mesh=mesh, plan=decoder_lm_plan())
        init_state, step, shard_batch = make_train_step(model, cfg, mesh, **step_kw)
        state = init_state(params)
        batch = shard_batch(toks)
        losses = []
        for _ in range(n_steps):
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
        return losses

    def test_no_data_axis_warns(self):
        # tp-only mesh: batch would be silently replicated on every
        # device (VERDICT r1 weak #7) -> make_train_step must warn.
        mesh = make_mesh({"tp": 8})
        model = make_llama(TINY)
        with pytest.warns(UserWarning, match="REPLICATED"):
            make_train_step(model, TINY, mesh)

    def test_dense_2d(self):
        losses = self._run(TINY, make_llama, {"dp": 2, "fsdp": 2, "tp": 2})
        assert losses[-1] < losses[0]
        assert all(np.isfinite(losses))

    def test_moe_expert_parallel(self):
        losses = self._run(TINY_MOE, make_mixtral, {"dp": 2, "ep": 2, "tp": 2})
        assert losses[-1] < losses[0]

    @pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
    def test_pipeline_matches_dense_losses(self, schedule):
        dense = self._run(TINY, make_llama, {"dp": 2, "fsdp": 2, "tp": 2})
        piped = self._run(
            TINY, make_llama, {"pp": 2, "dp": 2, "tp": 2},
            pipeline=True, n_microbatches=4, pipeline_schedule=schedule,
        )
        np.testing.assert_allclose(dense, piped, rtol=1e-4)


class TestGraftEntry:
    def test_entry_compiles(self):
        import __graft_entry__ as g

        fn, args = g.entry()
        out = jax.jit(fn)(*args)
        assert out.shape[-1] == TINY.vocab_size

    def test_dryrun_8(self):
        import __graft_entry__ as g

        g.dryrun_multichip(8)

    def test_dryrun_odd(self):
        import __graft_entry__ as g

        g.dryrun_multichip(4)


class TestHybridMesh:
    """make_hybrid_mesh: DCN axes stride across (virtual) slices, ICI axes
    stay within one — the multi-slice layout where tp/sp collectives must
    never cross DCN."""

    def test_ici_axes_stay_within_slice(self):
        from torchdistx_tpu.parallel import make_hybrid_mesh

        devs = jax.devices()
        mesh = make_hybrid_mesh({"dp": 2}, {"fsdp": 2, "tp": 2}, num_slices=2)
        assert mesh.axis_names == ("dp", "fsdp", "tp")
        assert mesh.devices.shape == (2, 2, 2)
        # Virtual slice i == contiguous block i of the device list; every
        # (fsdp, tp) submesh at fixed dp must be wholly inside one block.
        for i in range(2):
            ids = {d.id for d in mesh.devices[i].flat}
            expected = {d.id for d in devs[i * 4 : (i + 1) * 4]}
            assert ids == expected

    def test_axis_inference_and_errors(self):
        from torchdistx_tpu.parallel import make_hybrid_mesh

        mesh = make_hybrid_mesh({"dp": -1}, {"tp": -1}, num_slices=2)
        assert dict(zip(mesh.axis_names, mesh.devices.shape)) == {"dp": 2, "tp": 4}
        with pytest.raises(ValueError, match="multiply"):
            make_hybrid_mesh({"dp": 3}, {"tp": 4}, num_slices=2)
        with pytest.raises(ValueError, match="both"):
            make_hybrid_mesh({"dp": 2}, {"dp": 4}, num_slices=2)
        with pytest.raises(ValueError, match="divisible"):
            make_hybrid_mesh({"dp": -1}, {"tp": -1}, num_slices=3)

    def test_train_step_on_hybrid_mesh(self):
        from torchdistx_tpu.parallel import make_hybrid_mesh

        mesh = make_hybrid_mesh({"dp": 2}, {"fsdp": 2, "tp": 2}, num_slices=2)
        model = make_llama(TINY)
        toks = jax.random.randint(
            jax.random.PRNGKey(0), (8, 16), 0, TINY.vocab_size
        )
        fakes = deferred_init(model.init, jax.random.PRNGKey(0), toks)
        params = materialize(fakes, mesh=mesh, plan=decoder_lm_plan())
        init_state, step, shard_batch = make_train_step(model, TINY, mesh)
        state = init_state(params)
        state, metrics = step(state, shard_batch(toks))
        assert np.isfinite(float(metrics["loss"]))

    def test_initialize_multihost_single_process_noop(self):
        from torchdistx_tpu.parallel import initialize_multihost

        assert initialize_multihost() == jax.process_index()


class TestRingFlash:
    """Flash-kernel ring attention (parallel/ring_flash.py): forward and
    backward must match the dense oracle exactly — the backward is a real
    ring-flash second pass, not autodiff through the forward."""

    @pytest.fixture(scope="class")
    def mesh(self):
        return make_mesh({"dp": 2, "sp": 4})

    @pytest.mark.parametrize("causal", [True, False])
    @pytest.mark.parametrize("kv_heads", [4, 2])
    def test_matches_reference(self, mesh, causal, kv_heads):
        from torchdistx_tpu.parallel import make_ring_flash_attention

        B, S, H, D = 2, 32, 4, 16
        key = jax.random.PRNGKey(0)
        q = jax.random.normal(key, (B, S, H, D))
        k = jax.random.normal(jax.random.fold_in(key, 1), (B, S, kv_heads, D))
        v = jax.random.normal(jax.random.fold_in(key, 2), (B, S, kv_heads, D))
        attn = make_ring_flash_attention(mesh)
        ref = default_attention(q, k, v, causal=causal)
        out = jax.jit(lambda q, k, v: attn(q, k, v, causal=causal))(q, k, v)
        assert float(jnp.abs(ref - out).max()) < 1e-5

    @pytest.mark.parametrize("causal", [True, False])
    def test_gradients_match_reference(self, mesh, causal):
        from torchdistx_tpu.parallel import make_ring_flash_attention

        B, S, H, KV, D = 2, 32, 4, 2, 16
        key = jax.random.PRNGKey(3)
        q = jax.random.normal(key, (B, S, H, D))
        k = jax.random.normal(jax.random.fold_in(key, 1), (B, S, KV, D))
        v = jax.random.normal(jax.random.fold_in(key, 2), (B, S, KV, D))
        attn = make_ring_flash_attention(mesh)

        def loss(fn):
            return lambda q, k, v: (fn(q, k, v, causal=causal) ** 2).sum()

        g_ref = jax.grad(loss(default_attention), argnums=(0, 1, 2))(q, k, v)
        g_out = jax.jit(jax.grad(loss(attn), argnums=(0, 1, 2)))(q, k, v)
        for gr, go, name in zip(g_ref, g_out, "qkv"):
            err = float(jnp.abs(gr - go).max())
            assert err < 1e-4, f"d{name} mismatch: {err}"

    @pytest.mark.parametrize("causal", [True, False])
    def test_segment_ids_in_flash_ring(self, mesh, causal):
        # Packed sequences over the ring: query ids row-sharded, key ids
        # resident and column-sliced per step — fwd and grads vs oracle.
        from torchdistx_tpu.parallel import make_ring_flash_attention

        B, S, H, D = 2, 32, 4, 16
        key = jax.random.PRNGKey(11)
        q = jax.random.normal(key, (B, S, H, D))
        k = jax.random.normal(jax.random.fold_in(key, 1), (B, S, H, D))
        v = jax.random.normal(jax.random.fold_in(key, 2), (B, S, H, D))
        seg = jnp.concatenate(
            [jnp.zeros((B, 12), jnp.int32), jnp.ones((B, 8), jnp.int32),
             jnp.full((B, 12), 2, jnp.int32)], axis=1
        )
        attn = make_ring_flash_attention(mesh)
        ref = default_attention(q, k, v, causal=causal, segment_ids=seg)
        out = jax.jit(
            lambda q, k, v, s: attn(q, k, v, causal=causal, segment_ids=s)
        )(q, k, v, seg)
        assert float(jnp.abs(ref - out).max()) < 1e-5

        def loss(fn):
            return lambda q, k, v: (
                fn(q, k, v, causal=causal, segment_ids=seg) ** 2
            ).sum()

        g_ref = jax.grad(loss(default_attention), argnums=(0, 1, 2))(q, k, v)
        g_out = jax.jit(jax.grad(loss(attn), argnums=(0, 1, 2)))(q, k, v)
        for gr, go, name in zip(g_ref, g_out, "qkv"):
            err = float(jnp.abs(gr - go).max())
            assert err < 1e-4, f"d{name} mismatch: {err}"

    def test_segment_ids_in_dense_ring_and_ulysses(self, mesh):
        from torchdistx_tpu.parallel import (
            make_ring_attention, make_ulysses_attention,
        )

        B, S, H, D = 2, 32, 4, 16
        key = jax.random.PRNGKey(12)
        q = jax.random.normal(key, (B, S, H, D))
        k = jax.random.normal(jax.random.fold_in(key, 1), (B, S, H, D))
        v = jax.random.normal(jax.random.fold_in(key, 2), (B, S, H, D))
        seg = jnp.concatenate(
            [jnp.zeros((B, 16), jnp.int32), jnp.ones((B, 16), jnp.int32)],
            axis=1,
        )
        ref = default_attention(q, k, v, causal=True, segment_ids=seg)
        for make in (make_ring_attention, make_ulysses_attention):
            attn = make(mesh)
            out = jax.jit(
                lambda q, k, v, s: attn(q, k, v, causal=True, segment_ids=s)
            )(q, k, v, seg)
            assert float(jnp.abs(ref - out).max()) < 1e-5, make.__name__

    @pytest.mark.parametrize("causal", [True, False])
    def test_bias_runs_in_flash_ring(self, mesh, causal):
        # T5-style additive bias rides the flash kernels per ring step
        # (sharded [H, s, T] rows, per-step key-column slices) — fwd AND
        # bwd including dbias must match the dense oracle.
        from torchdistx_tpu.parallel import make_ring_flash_attention

        B, S, H, D = 2, 32, 4, 16
        key = jax.random.PRNGKey(5)
        q = jax.random.normal(key, (B, S, H, D))
        k = jax.random.normal(jax.random.fold_in(key, 1), (B, S, H, D))
        v = jax.random.normal(jax.random.fold_in(key, 2), (B, S, H, D))
        bias = jax.random.normal(jax.random.fold_in(key, 3), (H, S, S))
        attn = make_ring_flash_attention(mesh)
        ref = default_attention(q, k, v, causal=causal, bias=bias)
        out = jax.jit(lambda q, k, v, b: attn(q, k, v, causal=causal, bias=b))(
            q, k, v, bias
        )
        assert float(jnp.abs(ref - out).max()) < 1e-5

        def loss(fn):
            return lambda q, k, v, b: (
                fn(q, k, v, causal=causal, bias=b) ** 2
            ).sum()

        g_ref = jax.grad(loss(default_attention), argnums=(0, 1, 2, 3))(q, k, v, bias)
        g_out = jax.jit(jax.grad(loss(attn), argnums=(0, 1, 2, 3)))(q, k, v, bias)
        for gr, go, name in zip(g_ref, g_out, ["q", "k", "v", "bias"]):
            err = float(jnp.abs(gr - go).max())
            assert err < 1e-4, f"d{name} mismatch: {err}"

    def test_packed_model_trains_with_ring_flash(self, mesh):
        # Model-level packing: segment_ids flow tokens -> model ->
        # scan-stacked blocks -> ring-flash kernels, and the train step
        # masks next-token CE at packing boundaries.
        from torchdistx_tpu.parallel import make_ring_flash_attention
        from torchdistx_tpu.parallel.train import lm_cross_entropy

        cfg = TINY
        model = make_llama(cfg, attn_fn=make_ring_flash_attention(mesh))
        B, S = 8, 32
        toks = jax.random.randint(jax.random.PRNGKey(0), (B, S), 0, cfg.vocab_size)
        seg = jnp.concatenate(
            [jnp.zeros((B, 12), jnp.int32), jnp.ones((B, 20), jnp.int32)], axis=1
        )
        params = model.init(jax.random.PRNGKey(0), toks)

        # Packed forward == dense-oracle model with the same mask.
        dense = make_llama(cfg)
        ref = dense.apply(params, toks, segment_ids=seg)
        out = jax.jit(lambda p, t, s: model.apply(p, t, segment_ids=s))(
            params, toks, seg
        )
        assert float(jnp.abs(ref - out).max()) < 2e-4

        # Boundary masking: CE over packed logits ignores position 11
        # (next token belongs to the second document).
        full = lm_cross_entropy(ref, toks)
        masked = lm_cross_entropy(ref, toks, seg)
        assert full != masked
        # Padding convention: a negative-id tail contributes zero loss —
        # identical to simply truncating those positions.
        pad_seg = seg.at[:, 24:].set(-1)
        padded = lm_cross_entropy(ref, toks, pad_seg)
        trunc = lm_cross_entropy(ref[:, :24], toks[:, :24], seg[:, :24])
        assert float(jnp.abs(padded - trunc)) < 1e-6

        init_state, step, shard_batch = make_train_step(model, cfg, mesh)
        state = init_state(params)
        state, metrics = step(state, shard_batch(toks), shard_batch(seg))
        assert np.isfinite(float(metrics["loss"]))
        l0 = float(metrics["loss"])
        state, metrics = step(state, shard_batch(toks), shard_batch(seg))
        assert float(metrics["loss"]) < l0

    def test_model_trains_with_ring_flash(self, mesh):
        from torchdistx_tpu.parallel import make_ring_flash_attention

        attn = make_ring_flash_attention(mesh)
        model = make_llama(TINY, attn_fn=attn)
        toks = jax.random.randint(jax.random.PRNGKey(0), (4, 32), 0, TINY.vocab_size)
        params = model.init(jax.random.PRNGKey(0), toks)
        loss, grads = jax.value_and_grad(
            lambda p: (model.apply(p, toks) ** 2).mean()
        )(params)
        assert np.isfinite(float(loss))
        assert all(bool(jnp.isfinite(g).all()) for g in jax.tree.leaves(grads))

    def test_pipeline_composes_with_ring_flash(self):
        # Deepest composition: GPipe over pp x data parallel x ring-flash
        # sequence parallel, trained end to end on the virtual mesh.
        from torchdistx_tpu.models import decoder_lm_plan
        from torchdistx_tpu.parallel import make_ring_flash_attention
        from torchdistx_tpu.parallel.pipeline import pipeline_plan_overrides
        from torchdistx_tpu.parallel.sharding import ShardingPlan

        mesh = make_mesh({"pp": 2, "dp": 2, "sp": 2})
        attn = make_ring_flash_attention(mesh)
        model = make_llama(TINY, attn_fn=attn)
        toks = jax.random.randint(
            jax.random.PRNGKey(0), (8, 32), 0, TINY.vocab_size
        )
        fakes = deferred_init(model.init, jax.random.PRNGKey(0), toks)
        base = decoder_lm_plan(fsdp=None, ep=None)
        plan = ShardingPlan(
            pipeline_plan_overrides()
            + [(p.pattern, s) for p, s in base.rules]
        )
        params = materialize(fakes, mesh=mesh, plan=plan)
        init_state, step, shard_batch = make_train_step(
            model, TINY, mesh, pipeline=True, n_microbatches=4
        )
        state = init_state(params)
        losses = []
        for _ in range(3):
            state, metrics = step(state, shard_batch(toks))
            losses.append(float(metrics["loss"]))
        assert all(np.isfinite(x) for x in losses)
        assert losses[-1] < losses[0]


class TestUlyssesFlashComposition:
    """Ulysses all-to-all + pallas flash kernel as the per-device inner
    attention — the two long-context mechanisms composed the other way
    round from ring-flash (heads sharded, full sequence local)."""

    @pytest.fixture(scope="class")
    def mesh(self):
        return make_mesh({"dp": 2, "sp": 4})

    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_reference(self, mesh, causal):
        from torchdistx_tpu.ops import make_flash_attention

        B, S, H, D = 2, 32, 8, 16
        key = jax.random.PRNGKey(7)
        q = jax.random.normal(key, (B, S, H, D))
        k = jax.random.normal(jax.random.fold_in(key, 1), (B, S, H, D))
        v = jax.random.normal(jax.random.fold_in(key, 2), (B, S, H, D))
        attn = make_ulysses_attention(mesh, inner_attn=make_flash_attention())
        ref = default_attention(q, k, v, causal=causal)
        out = jax.jit(lambda q, k, v: attn(q, k, v, causal=causal))(q, k, v)
        assert float(jnp.abs(ref - out).max()) < 1e-5

    def test_gradients_match(self, mesh):
        from torchdistx_tpu.ops import make_flash_attention

        B, S, H, D = 2, 32, 8, 16
        key = jax.random.PRNGKey(9)
        q = jax.random.normal(key, (B, S, H, D))
        k = jax.random.normal(jax.random.fold_in(key, 1), (B, S, H, D))
        v = jax.random.normal(jax.random.fold_in(key, 2), (B, S, H, D))
        attn = make_ulysses_attention(mesh, inner_attn=make_flash_attention())

        def loss(fn):
            return lambda q, k, v: (fn(q, k, v) ** 2).sum()

        g_ref = jax.grad(loss(default_attention), argnums=(0, 1, 2))(q, k, v)
        g_out = jax.jit(jax.grad(loss(attn), argnums=(0, 1, 2)))(q, k, v)
        for gr, go, name in zip(g_ref, g_out, "qkv"):
            assert float(jnp.abs(gr - go).max()) < 1e-4, f"d{name}"


class TestGspmd2dPlan:
    def test_two_largest_dims_take_both_axes(self):
        from torchdistx_tpu.parallel import gspmd_2d_plan, make_mesh
        from jax.sharding import PartitionSpec as P

        mesh = make_mesh({"fsdp": 4, "tp": 2})
        plan = gspmd_2d_plan(min_size=1)
        # [1024, 64]: fsdp (size 4) on dim 0 (largest), tp (2) on dim 1.
        assert plan.spec_for("enc.w", (1024, 64), mesh) == P("fsdp", "tp")
        # 3D: the two largest dims take the axes, smallest stays None.
        assert plan.spec_for("m.w", (8, 128, 64), mesh) == P(None, "fsdp", "tp")

    def test_indivisible_dim_degrades(self):
        from torchdistx_tpu.parallel import gspmd_2d_plan, make_mesh
        from jax.sharding import PartitionSpec as P

        mesh = make_mesh({"fsdp": 4, "tp": 2})
        plan = gspmd_2d_plan(min_size=1)
        # dim0 127 not divisible by 4: fsdp falls to dim 1; tp (size 2)
        # cannot re-use it, and 127 is odd, so tp is dropped.
        assert plan.spec_for("m.w", (127, 64), mesh) == P(None, "fsdp")

    def test_small_tensor_replicates(self):
        from torchdistx_tpu.parallel import gspmd_2d_plan, make_mesh
        from jax.sharding import PartitionSpec as P

        mesh = make_mesh({"fsdp": 4, "tp": 2})
        plan = gspmd_2d_plan(min_size=2**16)
        assert plan.spec_for("m.bias", (64,), mesh) == P()

    def test_size_one_axis_does_not_claim_dims(self):
        from torchdistx_tpu.parallel import gspmd_2d_plan, make_mesh
        from jax.sharding import PartitionSpec as P

        # A no-op (size-1) fsdp axis must not block tp from the largest
        # dim: (65536, 100) on {'fsdp':1,'tp':8} shards dim 0 over tp.
        mesh = make_mesh({"fsdp": 1, "tp": 8})
        plan = gspmd_2d_plan(min_size=1)
        assert plan.spec_for("m.w", (65536, 100), mesh) == P("tp", None)


class TestCpuBf16PipelineGuard:
    def test_bf16_pipeline_on_cpu_mesh_raises_clearly(self):
        # bf16 + any pipelined schedule makes XLA:CPU's compiler abort
        # the whole process (hlo_instruction.cc 'Invalid binary
        # instruction opcode copy') — make_train_step must refuse with
        # a catchable error instead.  Cannot be tested by letting it
        # crash: the abort would kill pytest itself.
        import dataclasses

        import pytest

        from torchdistx_tpu.models import TINY, make_llama
        from torchdistx_tpu.parallel import make_mesh
        from torchdistx_tpu.parallel.train import make_train_step

        cfg = dataclasses.replace(TINY, dtype=jnp.bfloat16)
        mesh = make_mesh({"pp": 2, "dp": 4})
        with pytest.raises(RuntimeError, match="XLA:CPU"):
            make_train_step(make_llama(cfg), cfg, mesh, pipeline=True)

    def test_f32_pipeline_and_bf16_dense_still_build(self):
        import dataclasses

        from torchdistx_tpu.models import TINY, make_llama
        from torchdistx_tpu.parallel import make_mesh
        from torchdistx_tpu.parallel.train import make_train_step

        mesh = make_mesh({"pp": 2, "dp": 4})
        make_train_step(make_llama(TINY), TINY, mesh, pipeline=True)
        cfg = dataclasses.replace(TINY, dtype=jnp.bfloat16)
        dense_mesh = make_mesh({"dp": 8})
        make_train_step(make_llama(cfg), cfg, dense_mesh)
