"""Streaming materialize transport (docs/performance.md §transport).

Covers the ISSUE-9 transport layer: the donation/overlap/batching knob
parity matrix against a fault-free monolith, the batched per-sharding
``device_put`` helper (and the resume path riding it), the donated
commit program's aliasing/consumption semantics and its retry ladder
(consumed donated inputs regenerate via the producer; the final rung
compiles non-donating), the ``TDX_MATERIALIZE_INIT_DTYPE=bf16`` fast
path's two-tier parity contract (exact-bitwise where the contract dtype
already is bf16; exactly-the-bf16-rounding-of-default otherwise), the
chaos ``execute`` site with donation enabled, and the swept link probe.

Kept lean for tier-1: one small recorded model shared per scenario
family, one persistent-cache dir for the whole module (everything after
the first compile of each program set is a warm hit), multi-second
cases ``slow``-marked (``make chaos-test`` runs them).
"""

import numpy as np
import pytest
import torch

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

import torchdistx_tpu.config as tdx_config
from torchdistx_tpu import observe
from torchdistx_tpu.deferred_init import deferred_init
from torchdistx_tpu.jax_bridge import materialize as mat
from torchdistx_tpu.jax_bridge import materialize_module_jax, transport

K = 10  # layers; distinct widths defeat batching → a real multi-group split


class Pyramid(torch.nn.Module):
    def __init__(self):
        super().__init__()
        w = [8 + 4 * i for i in range(K)]
        self.layers = torch.nn.ModuleList(
            torch.nn.Linear(w[i], w[(i + 1) % K]) for i in range(K)
        )
        # An f32 BUFFER: ineligible for the init-dtype cast, so under
        # the bf16 transport it rides the donated commit program as a
        # pass-through slot (the aliasing case).
        self.register_buffer("scale", torch.ones(64))


@pytest.fixture(scope="module")
def cache_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("transport_cache")
    mat._reset_cache_binding()
    yield str(d)
    mat._reset_cache_binding()


def _run(mode, cache_dir, *, seed=0, param_dtype=None, **kw):
    with tdx_config.override(
        materialize_pipeline=mode, cache_dir=cache_dir, **kw
    ):
        m = deferred_init(Pyramid)
        vals = materialize_module_jax(m, seed=seed, param_dtype=param_dtype)
    return {k: np.asarray(v) for k, v in vals.items()}


def _assert_bitwise(a, b):
    assert set(a) == set(b)
    for k in a:
        assert a[k].dtype == b[k].dtype, k
        assert np.array_equal(a[k], b[k]), k


@pytest.fixture(scope="module")
def ref(cache_dir):
    """Fault-free monolith, default transport config — THE oracle."""
    return _run("off", cache_dir)


@pytest.fixture(scope="module")
def ref_bf16(cache_dir):
    """Fault-free monolith under the bf16 init fast path."""
    return _run("off", cache_dir, materialize_init_dtype="bf16")


# -- knob parity matrix -------------------------------------------------------


@pytest.mark.parametrize("mode", ["off", "auto"])
@pytest.mark.parametrize("donate", [True, False])
@pytest.mark.parametrize("depth", [1, 3])
def test_parity_matrix(mode, donate, depth, ref, cache_dir):
    """Donation on/off × overlap depth × both engines: bitwise-equal to
    the fault-free monolith (the knobs change how bytes move, never
    which bits land)."""
    vals = _run(mode, cache_dir, materialize_donate=donate,
                materialize_overlap_depth=depth)
    _assert_bitwise(vals, ref)


def test_per_leaf_transfer_parity(ref, cache_dir):
    """The batching escape hatch (TDX_MATERIALIZE_BATCH_PUT=0) changes
    dispatch count only, never values."""
    vals = _run("auto", cache_dir, materialize_batch_put=False)
    _assert_bitwise(vals, ref)


def test_pipelined_engine_engaged(cache_dir):
    """The module's model must actually exercise the pipelined engine —
    otherwise the matrix above silently tests the monolith twice."""
    _run("auto", cache_dir)
    stats = mat.last_run_stats()
    assert stats["mode"] == "pipelined"
    assert stats["n_programs"] >= 2
    for key in ("bytes_donated", "transfer_overlap", "device_put_batches"):
        assert key in stats


# -- batched per-sharding device_put ------------------------------------------


def test_batched_device_put_groups_by_sharding():
    mesh = Mesh(np.array(jax.devices()[:2]).reshape(2), ("d",))
    s_rep = NamedSharding(mesh, PartitionSpec())
    s_shard = NamedSharding(mesh, PartitionSpec("d"))
    arrs = [
        np.arange(4, dtype=np.float32),
        np.arange(8, dtype=np.float32),
        np.ones(6, dtype=np.int32),
        np.full(8, 7.0, dtype=np.float32),
    ]
    shardings = [s_rep, s_shard, s_rep, s_shard]
    c0 = observe.counter("tdx.jax.device_put_batches").value
    vals, n = transport.batched_device_put(arrs, shardings)
    assert n == 2  # one dispatch per distinct sharding
    assert observe.counter("tdx.jax.device_put_batches").value - c0 == 2
    for v, a, s in zip(vals, arrs, shardings):
        assert np.array_equal(np.asarray(v), a)
        assert v.sharding == s


def test_batched_device_put_no_shardings_single_batch():
    vals, n = transport.batched_device_put(
        [np.arange(3, dtype=np.float32), np.ones(2, dtype=np.float32)]
    )
    assert n == 1
    assert np.array_equal(np.asarray(vals[0]), [0, 1, 2])


def test_resume_group_batched_vs_per_leaf(tmp_path):
    """_try_resume_group loads a committed group in ONE batched dispatch
    per distinct sharding (the materialize.py:1107 satellite), per-leaf
    only under the escape hatch — same values either way."""
    mesh = Mesh(np.array(jax.devices()[:2]).reshape(2), ("d",))
    osh = [NamedSharding(mesh, PartitionSpec())] * 3
    values = [np.arange(6, dtype=np.float32) + i for i in range(3)]
    rdir = str(tmp_path)
    manifest = {}
    mat._commit_resume_group(rdir, manifest, "a" * 40, [0, 1, 2],
                             values)
    rec = manifest["a" * 40]
    c0 = observe.counter("tdx.jax.device_put_batches").value
    loaded = mat._try_resume_group(rdir, "a" * 40, rec, [0, 1, 2], osh,
                                   batch_put=True)
    assert loaded is not None
    vals, n = loaded
    assert n == 1  # all three share one sharding → one dispatch
    assert observe.counter("tdx.jax.device_put_batches").value - c0 == 1
    for v, a in zip(vals, values):
        assert np.array_equal(np.asarray(v), a)
    vals2, n2 = mat._try_resume_group(rdir, "a" * 40, rec, [0, 1, 2], osh,
                                      batch_put=False)
    assert n2 == 0
    for v, a in zip(vals2, values):
        assert np.array_equal(np.asarray(v), a)


# -- donated commit program ---------------------------------------------------


def _toy_plan_and_outs():
    mesh = Mesh(np.array(jax.devices()[:1]), ("d",))
    sh = NamedSharding(mesh, PartitionSpec())
    plan = transport.plan_transport(
        [jnp.float32, jnp.float32], [True, False], jnp.bfloat16, [sh, sh]
    )

    def producer():
        return (
            jax.device_put(jnp.arange(8, dtype=jnp.bfloat16), sh),
            jax.device_put(jnp.ones(4, dtype=jnp.float32), sh),
        )

    return plan, producer


def test_commit_donation_aliases_and_consumes():
    """With donation, a pass-through slot aliases its buffer (pointer
    equality — the 'no defensive copy' assertion) and is consumed
    (is_deleted); a converting slot upcasts to its contract dtype.
    Donated bytes are counted."""
    plan, producer = _toy_plan_and_outs()
    outs = producer()
    passthrough = outs[1]
    p_in = passthrough.unsafe_buffer_pointer()
    c0 = observe.counter("tdx.jax.bytes_donated").value
    final, donated = transport.commit_outputs(
        outs, plan, donate=True, producer=producer, retries=2,
        retryable=(),
    )
    assert final[0].dtype == jnp.float32
    assert np.array_equal(np.asarray(final[0]), np.arange(8))
    assert passthrough.is_deleted()
    assert final[1].unsafe_buffer_pointer() == p_in
    assert donated >= passthrough.size * 4
    assert observe.counter("tdx.jax.bytes_donated").value - c0 == donated


def test_commit_without_donation_leaves_passthrough_untouched():
    plan, producer = _toy_plan_and_outs()
    outs = producer()
    final, donated = transport.commit_outputs(
        outs, plan, donate=False, producer=producer, retries=0,
        retryable=(),
    )
    assert donated == 0
    assert not outs[1].is_deleted()
    assert final[1] is outs[1]  # never entered the commit program
    assert final[0].dtype == jnp.float32


def test_commit_retry_regenerates_consumed_inputs():
    """A donated buffer must not be consumed twice: feeding already-
    consumed outputs re-runs the producer (idempotent — the PRNG key is
    never donated)."""
    plan, producer = _toy_plan_and_outs()
    calls = []

    def counting_producer():
        calls.append(1)
        return producer()

    outs = producer()
    transport.commit_outputs(outs, plan, donate=True,
                             producer=counting_producer, retries=2,
                             retryable=(RuntimeError,))
    # `outs` are now consumed; committing them again must regenerate.
    final, _ = transport.commit_outputs(
        outs, plan, donate=True, producer=counting_producer, retries=2,
        retryable=(RuntimeError,),
    )
    assert len(calls) == 1
    assert np.array_equal(np.asarray(final[0]), np.arange(8))


def test_commit_final_retry_non_donating(monkeypatch):
    """Donation itself must never be able to fail every rung: the final
    retry compiles a non-donating commit program."""
    plan, producer = _toy_plan_and_outs()
    orig = transport._commit_program
    donate_calls = []

    def failing_donating(shapes, src, dst, osh, donate):
        if donate:
            donate_calls.append(1)
            raise RuntimeError("injected: donating commit rejected")
        return orig(shapes, src, dst, osh, donate)

    monkeypatch.setattr(transport, "_commit_program", failing_donating)
    c0 = observe.counter("tdx.jax.commit_retries").value
    final, donated = transport.commit_outputs(
        producer(), plan, donate=True, producer=producer, retries=2,
        retryable=(RuntimeError,),
    )
    assert donated == 0  # delivered by the non-donating rung
    assert len(donate_calls) == 2  # attempts 0 and 1 tried donation
    assert observe.counter("tdx.jax.commit_retries").value - c0 == 2
    assert np.array_equal(np.asarray(final[0]), np.arange(8))


# -- plan / init-dtype resolution ---------------------------------------------


def test_resolve_init_dtype():
    assert transport.resolve_init_dtype(None) is None
    assert transport.resolve_init_dtype("") is None
    assert transport.resolve_init_dtype("bf16") == jnp.bfloat16
    assert transport.resolve_init_dtype("bfloat16") == jnp.bfloat16
    with pytest.raises(ValueError):
        transport.resolve_init_dtype("int8")  # not floating
    with pytest.raises(ValueError):
        transport.resolve_init_dtype("no-such-dtype")


def test_plan_transport_eligibility():
    # f32 param → converts; f32 buffer (mask False) → pass-through;
    # bf16/f16 contracts (equal width) and ints → no plan member.
    plan = transport.plan_transport(
        [jnp.float32, jnp.float32, jnp.bfloat16, jnp.int32],
        [True, False, True, True], jnp.bfloat16,
    )
    assert plan is not None and plan.converts
    assert plan.storage == (jnp.bfloat16, None, None, None)
    # nothing eligible → None (the engines run their default path)
    assert transport.plan_transport(
        [jnp.bfloat16, jnp.int32], [True, True], jnp.bfloat16
    ) is None
    assert transport.plan_transport(
        [jnp.float32], [True], None
    ) is None


# -- the bf16 init fast path --------------------------------------------------


def test_bf16_engines_agree_and_round_exactly(ref, ref_bf16, cache_dir):
    """The fast path's tolerance contract is EXACT: each value is the
    bf16 rounding of the default path's value (upcast back on device),
    and the two engines agree bitwise with each other.  Contract dtypes
    are preserved — f32 params stay f32, the f32 buffer is untouched."""
    import ml_dtypes

    auto = _run("auto", cache_dir, materialize_init_dtype="bf16")
    _assert_bitwise(auto, ref_bf16)
    stats = mat.last_run_stats()
    assert stats["mode"] == "pipelined"
    # The buffer pass-through slot makes donation real on this jax.
    assert stats["bytes_donated"] > 0
    for k, v in auto.items():
        assert v.dtype == ref[k].dtype
        expected = ref[k].astype(ml_dtypes.bfloat16).astype(ref[k].dtype)
        assert np.array_equal(v, expected), k


def test_bf16_exact_when_contract_is_bf16(cache_dir):
    """param_dtype=bf16 under the bf16 transport: contract dtype ==
    init dtype, no upcast exists, and the program is byte-identical to
    the default path's — exact-bitwise by construction."""
    a = _run("auto", cache_dir, param_dtype=jnp.bfloat16,
             materialize_init_dtype="bf16")
    b = _run("auto", cache_dir, param_dtype=jnp.bfloat16)
    _assert_bitwise(a, b)
    assert mat.last_run_stats()["bytes_donated"] == 0


@pytest.mark.slow
def test_chaos_execute_fault_with_donation(ref_bf16, cache_dir):
    """Chaos `execute` faults with donation + bf16 enabled: retries must
    not consume a donated buffer twice — the run survives bitwise-equal
    to the fault-free fast path."""
    vals = _run("auto", cache_dir, materialize_init_dtype="bf16",
                fault_plan="execute@2=raise")
    _assert_bitwise(vals, ref_bf16)


@pytest.mark.slow
def test_bf16_seed_variation(ref_bf16, cache_dir):
    """A different seed through the fast path reuses the same compiled
    programs (the PRNG key is a runtime argument) and still matches the
    rounded default."""
    import ml_dtypes

    base = _run("off", cache_dir, seed=7)
    fast = _run("auto", cache_dir, seed=7, materialize_init_dtype="bf16")
    assert any(not np.array_equal(fast[k], ref_bf16[k]) for k in fast)
    for k in fast:
        expected = base[k].astype(ml_dtypes.bfloat16).astype(base[k].dtype)
        assert np.array_equal(fast[k], expected), k


# -- serve bring-up plumbing --------------------------------------------------


def test_serve_init_fingerprint_salted_by_init_dtype():
    """The serving init program's registry fingerprint must change when
    the transport fast path is armed (the compiled bytes differ), while
    prefill/decode fingerprints stay stable; the init spec carries the
    upcast plan."""
    from torchdistx_tpu.models import PRESETS
    from torchdistx_tpu.serve.programs import ServeConfig, serve_program_specs

    cfg = PRESETS["tiny"]
    scfg = ServeConfig(max_batch=2, page_size=8, n_pages=8,
                       max_pages_per_seq=2, prefill_buckets=(8,))
    default = serve_program_specs("llama", cfg, scfg)
    with tdx_config.override(materialize_init_dtype="bf16"):
        fast = serve_program_specs("llama", cfg, scfg)
    d = {s.name: s for s in default}
    f = {s.name: s for s in fast}
    assert d["init"].tplan is None
    assert f["init"].tplan is not None and f["init"].tplan.converts
    assert d["init"].program_fp != f["init"].program_fp
    assert d["decode"].program_fp == f["decode"].program_fp
    # ShapeDtypeStructs keep the POST-upcast contract dtypes: the
    # lowered decode signature consumes what the upcast delivers.
    for s, st in zip(f["init"].tplan.final, f["init"].tplan.storage):
        if st is not None:
            assert jnp.dtype(s) == jnp.float32


# -- link probe sweep ---------------------------------------------------------


def test_link_probe_sweep(monkeypatch):
    from torchdistx_tpu.observe import costmodel

    monkeypatch.setenv("TDX_LINK_PROBE_MB", "1,2")
    costmodel.reset_link_probe()
    try:
        gbps = costmodel.link_bandwidth_gbps()
        assert gbps and gbps > 0
        assert costmodel.link_probe_size_mb() in (1, 2)
        # cached_only returns the cached sweep result without re-probing
        assert costmodel.link_bandwidth_gbps(cached_only=True) == gbps
    finally:
        costmodel.reset_link_probe()
