"""Sphinx configuration for the rendered doc site.

Reference parity: the reference ships Sphinx docs plus a publish
workflow (reference docs/src/conf.py:1, .github/workflows/push_doc.yaml:1).
This build's documentation is markdown-first (the files in this
directory), so the Sphinx layer is thin: myst-parser renders the same
markdown into a navigable site.  Built by ``make docs`` and CI
(.github/workflows/docs.yaml); the dev image has no sphinx, so the
local target degrades to a skip with a message.
"""

import pathlib

project = "torchdistx_tpu"
copyright = "2026, the torchdistx_tpu authors"
author = "the torchdistx_tpu authors"
release = (
    pathlib.Path(__file__).resolve().parent.parent / "VERSION"
).read_text().strip()

extensions = ["myst_parser"]
source_suffix = {".md": "markdown", ".rst": "restructuredtext"}
master_doc = "index"
exclude_patterns = ["_build"]

html_theme = "furo"
html_title = f"torchdistx_tpu {release}"
myst_heading_anchors = 3
