// Native unit tests for the graph engine (run under ASan/UBSan in CI —
// the runtime sanitizer coverage the reference lacked, its tests/cc was
// an acknowledged TODO, reference CMakeLists.txt:104-106).
//
// Build/run: make native-test

#include <cassert>
#include <cstdint>
#include <cstdio>
#include <vector>

extern "C" {
void* tdx_graph_create();
void tdx_graph_destroy(void*);
uint64_t tdx_node_create(void*);
void tdx_node_destroy(void*, uint64_t);
void tdx_node_add_storage(void*, uint64_t, uint64_t);
void tdx_node_add_dep(void*, uint64_t, uint64_t, int32_t);
void tdx_node_set_materialized(void*, uint64_t, int32_t);
uint64_t tdx_last_in_place(void*, uint64_t);
uint64_t tdx_build_call_stack(void*, uint64_t, uint64_t*, uint64_t);
}

static std::vector<uint64_t> stack_of(void* g, uint64_t id) {
  uint64_t buf[64];
  uint64_t n = tdx_build_call_stack(g, id, buf, 64);
  assert(n <= 64);
  return std::vector<uint64_t>(buf, buf + n);
}

int main() {
  // Scenario from tests/test_deferred_init.py::test_in_place_through_view:
  //   n1 = empty(w)        storage S
  //   n2 = fill_(w)        storage S  (dep n1)
  //   n3 = select(w)->v    storage S  (dep n2)
  //   n4 = add_(v)         storage S  (dep n3)
  //   n5 = mul_(w)         storage S  (dep n2!)  <- w's ctx was n2
  void* g = tdx_graph_create();
  uint64_t n1 = tdx_node_create(g);
  uint64_t n2 = tdx_node_create(g);
  uint64_t n3 = tdx_node_create(g);
  uint64_t n4 = tdx_node_create(g);
  uint64_t n5 = tdx_node_create(g);
  const uint64_t S = 0xABCD;
  for (uint64_t n : {n1, n2, n3, n4, n5}) tdx_node_add_storage(g, n, S);
  tdx_node_add_dep(g, n2, n1, 0);
  tdx_node_add_dep(g, n3, n2, 0);
  tdx_node_add_dep(g, n4, n3, 0);
  tdx_node_add_dep(g, n5, n2, 0);

  // materialize(w) at n5: last in place is n5 itself; stack must include
  // the view chain n3,n4 (they alias S) in chronological order.
  assert(tdx_last_in_place(g, n5) == n5);
  auto s = stack_of(g, n5);
  assert((s == std::vector<uint64_t>{n1, n2, n3, n4, n5}));

  // materialize(v) at n4: the later mutation n5 of the shared storage is
  // INCLUDED — eager semantics (v is a view of w; mul_(w) changes v). The
  // bidirectional last-in-place walk reaches n5 via the dependency edge
  // n4 -> n3 -> n2 -> dependent n5 (the reference's dependents-only walk
  // missed it and replayed the stale value).
  assert(tdx_last_in_place(g, n4) == n5);
  auto sv = stack_of(g, n4);
  assert((sv == std::vector<uint64_t>{n1, n2, n3, n4, n5}));

  // last-in-place from the producer n2 must find n5.
  assert(tdx_last_in_place(g, n2) == n5);

  // Materialized nodes prune the dependency closure: with the whole
  // prefix replayed (as a real materialize would have done — replayed
  // real tensors carry alias state), only the requested node remains.
  for (uint64_t n : {n1, n2, n3, n4}) tdx_node_set_materialized(g, n, 1);
  auto sm = stack_of(g, n5);
  assert((sm == std::vector<uint64_t>{n5}));

  // node destruction erases back-edges: destroy n5, n2's dependents must
  // no longer reach it.
  tdx_node_destroy(g, n5);
  assert(tdx_last_in_place(g, n4) == n4);

  // clobbered reader: r reads storage A's output (no alias), then an
  // in-place op clobbers A before the requested node.
  //   a1 = empty (A); r = mm(a) -> storage R; a2 = mul_(a) (A, dep a1)
  uint64_t a1 = tdx_node_create(g);
  uint64_t r = tdx_node_create(g);
  uint64_t a2 = tdx_node_create(g);
  tdx_node_add_storage(g, a1, 0x1);
  tdx_node_add_storage(g, r, 0x2);
  tdx_node_add_storage(g, a2, 0x1);
  tdx_node_add_dep(g, r, a1, 0);
  tdx_node_add_dep(g, a2, a1, 0);
  auto sc = stack_of(g, a2);
  assert((sc == std::vector<uint64_t>{a1, r, a2}));  // r pulled in before a2

  // buffer-too-small path returns the true count without overflow.
  uint64_t tiny[1];
  uint64_t need = tdx_build_call_stack(g, a2, tiny, 1);
  assert(need == 3);

  tdx_graph_destroy(g);
  std::puts("native graph tests OK");
  return 0;
}
