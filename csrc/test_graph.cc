// Native unit tests for the graph engine (run under ASan/UBSan and TSan
// in CI — the runtime sanitizer coverage the reference lacked, its
// tests/cc was an acknowledged TODO, reference CMakeLists.txt:104-106).
//
// Build/run: make native-test

#include <atomic>
#include <cassert>
#include <cstdint>
#include <cstdio>
#include <thread>
#include <vector>

extern "C" {
void* tdx_graph_create();
void tdx_graph_destroy(void*);
uint64_t tdx_node_create(void*);
void tdx_node_destroy(void*, uint64_t);
void tdx_node_add_storage(void*, uint64_t, uint64_t);
void tdx_node_add_dep(void*, uint64_t, uint64_t, int32_t);
void tdx_node_set_materialized(void*, uint64_t, int32_t);
uint64_t tdx_last_in_place(void*, uint64_t);
uint64_t tdx_build_call_stack(void*, uint64_t, uint64_t*, uint64_t);
}

static std::vector<uint64_t> stack_of(void* g, uint64_t id) {
  uint64_t buf[64];
  uint64_t n = tdx_build_call_stack(g, id, buf, 64);
  assert(n <= 64);
  return std::vector<uint64_t>(buf, buf + n);
}

// Concurrency stress: recorder threads append alias chains (create /
// add_storage / add_dep / set_materialized / destroy) while materializer
// threads walk last-in-place and call stacks over whatever ids have been
// published — the exact interleaving the reference guards with its graph
// mutex (deferred_init.cc:1106-1129: recording on one thread while
// materializing on another).  Every C API call locks the graph's mutex,
// so `make native-test SAN="-fsanitize=thread"` must come back green;
// that TSan lane is the contract this test exists to keep.
static void stress_record_while_materializing() {
  void* g = tdx_graph_create();
  constexpr int kRecorders = 4;
  constexpr int kMaterializers = 3;
  constexpr int kOps = 1200;
  std::atomic<uint64_t> max_id{0};
  std::atomic<bool> recording{true};

  std::vector<std::thread> threads;
  for (int t = 0; t < kRecorders; ++t) {
    threads.emplace_back([&, t] {
      uint64_t prev = 0;
      for (int i = 0; i < kOps; ++i) {
        uint64_t id = tdx_node_create(g);
        // Storage keys cycle over a small shared set so materializer
        // walks cross alias boundaries authored by other threads.
        tdx_node_add_storage(g, id,
                             0x100 + static_cast<uint64_t>((t + i) % 4));
        if (prev) tdx_node_add_dep(g, id, prev, 0);
        if (i % 7 == 3) tdx_node_set_materialized(g, id, 1);
        if (i % 11 == 5 && prev) {
          tdx_node_destroy(g, prev);
          prev = 0;
        } else {
          prev = id;
        }
        uint64_t cur = max_id.load(std::memory_order_relaxed);
        while (id > cur && !max_id.compare_exchange_weak(
                               cur, id, std::memory_order_relaxed)) {
        }
      }
    });
  }
  for (int t = 0; t < kMaterializers; ++t) {
    threads.emplace_back([&, t] {
      uint64_t buf[256];
      uint64_t probe = static_cast<uint64_t>(t) + 1;
      while (recording.load(std::memory_order_relaxed)) {
        uint64_t hi = max_id.load(std::memory_order_relaxed);
        if (hi == 0) continue;
        probe = probe * 2654435761ull + 1;  // cheap deterministic hash walk
        uint64_t id = 1 + probe % hi;
        tdx_last_in_place(g, id);  // 0 (destroyed) or a live node id
        uint64_t n = tdx_build_call_stack(g, id, buf, 256);
        if (n > 0 && n <= 256) {
          // Chronological order == ascending ids (op_nr tracks next_id),
          // even for stacks snapshotted mid-recording.
          for (uint64_t k = 1; k < n; ++k) assert(buf[k - 1] < buf[k]);
        }
      }
    });
  }
  for (int t = 0; t < kRecorders; ++t) threads[t].join();
  recording.store(false);
  for (size_t t = kRecorders; t < threads.size(); ++t) threads[t].join();

  // The graph must still answer exact queries after the storm.
  uint64_t b1 = tdx_node_create(g);
  uint64_t b2 = tdx_node_create(g);
  tdx_node_add_storage(g, b1, 0xBEEF);
  tdx_node_add_storage(g, b2, 0xBEEF);
  tdx_node_add_dep(g, b2, b1, 0);
  assert(tdx_last_in_place(g, b1) == b2);
  auto s = stack_of(g, b2);
  assert((s == std::vector<uint64_t>{b1, b2}));
  tdx_graph_destroy(g);
}

int main() {
  // Scenario from tests/test_deferred_init.py::test_in_place_through_view:
  //   n1 = empty(w)        storage S
  //   n2 = fill_(w)        storage S  (dep n1)
  //   n3 = select(w)->v    storage S  (dep n2)
  //   n4 = add_(v)         storage S  (dep n3)
  //   n5 = mul_(w)         storage S  (dep n2!)  <- w's ctx was n2
  void* g = tdx_graph_create();
  uint64_t n1 = tdx_node_create(g);
  uint64_t n2 = tdx_node_create(g);
  uint64_t n3 = tdx_node_create(g);
  uint64_t n4 = tdx_node_create(g);
  uint64_t n5 = tdx_node_create(g);
  const uint64_t S = 0xABCD;
  for (uint64_t n : {n1, n2, n3, n4, n5}) tdx_node_add_storage(g, n, S);
  tdx_node_add_dep(g, n2, n1, 0);
  tdx_node_add_dep(g, n3, n2, 0);
  tdx_node_add_dep(g, n4, n3, 0);
  tdx_node_add_dep(g, n5, n2, 0);

  // materialize(w) at n5: last in place is n5 itself; stack must include
  // the view chain n3,n4 (they alias S) in chronological order.
  assert(tdx_last_in_place(g, n5) == n5);
  auto s = stack_of(g, n5);
  assert((s == std::vector<uint64_t>{n1, n2, n3, n4, n5}));

  // materialize(v) at n4: the later mutation n5 of the shared storage is
  // INCLUDED — eager semantics (v is a view of w; mul_(w) changes v). The
  // bidirectional last-in-place walk reaches n5 via the dependency edge
  // n4 -> n3 -> n2 -> dependent n5 (the reference's dependents-only walk
  // missed it and replayed the stale value).
  assert(tdx_last_in_place(g, n4) == n5);
  auto sv = stack_of(g, n4);
  assert((sv == std::vector<uint64_t>{n1, n2, n3, n4, n5}));

  // last-in-place from the producer n2 must find n5.
  assert(tdx_last_in_place(g, n2) == n5);

  // Materialized nodes prune the dependency closure: with the whole
  // prefix replayed (as a real materialize would have done — replayed
  // real tensors carry alias state), only the requested node remains.
  for (uint64_t n : {n1, n2, n3, n4}) tdx_node_set_materialized(g, n, 1);
  auto sm = stack_of(g, n5);
  assert((sm == std::vector<uint64_t>{n5}));

  // node destruction erases back-edges: destroy n5, n2's dependents must
  // no longer reach it.
  tdx_node_destroy(g, n5);
  assert(tdx_last_in_place(g, n4) == n4);

  // clobbered reader: r reads storage A's output (no alias), then an
  // in-place op clobbers A before the requested node.
  //   a1 = empty (A); r = mm(a) -> storage R; a2 = mul_(a) (A, dep a1)
  uint64_t a1 = tdx_node_create(g);
  uint64_t r = tdx_node_create(g);
  uint64_t a2 = tdx_node_create(g);
  tdx_node_add_storage(g, a1, 0x1);
  tdx_node_add_storage(g, r, 0x2);
  tdx_node_add_storage(g, a2, 0x1);
  tdx_node_add_dep(g, r, a1, 0);
  tdx_node_add_dep(g, a2, a1, 0);
  auto sc = stack_of(g, a2);
  assert((sc == std::vector<uint64_t>{a1, r, a2}));  // r pulled in before a2

  // buffer-too-small path returns the true count without overflow.
  uint64_t tiny[1];
  uint64_t need = tdx_build_call_stack(g, a2, tiny, 1);
  assert(need == 3);

  tdx_graph_destroy(g);

  stress_record_while_materializing();

  std::puts("native graph tests OK");
  return 0;
}
