// Native replay-graph topology engine for torchdistx_tpu.
//
// TPU-native counterpart of the reference's C++ OpNode machinery
// (/root/reference/src/cc/torchdistx/deferred_init.cc:309-705): node
// creation order (op_nr), output-storage alias tracking, dependency /
// dependent edges, last-in-place-walk and call-stack collection.  The
// Python layer keeps the op closures and preserved argument stacks (they
// are Python objects); this library owns the graph *topology* and the
// hot graph walks, and reproduces the reference's ownership semantics:
// a node's destructor erases its back-edges from its dependencies
// (deferred_init.cc:409-411), driven here by the Python wrapper's
// lifetime via tdx_node_destroy.
//
// Exposed as a plain C API for ctypes (no pybind11 in this image).

#include <algorithm>
#include <cstdint>
#include <mutex>
#include <unordered_map>
#include <unordered_set>
#include <vector>

#define TDX_BUILDING_DLL
#include "include/tdx_graph.h"  // public C API — keeps signatures in sync

#define TDX_API extern "C" __attribute__((visibility("default")))

namespace {

struct Node {
  uint64_t id = 0;
  uint64_t op_nr = 0;
  bool materialized = false;
  std::vector<uint64_t> storages;
  std::vector<std::pair<uint64_t, int32_t>> deps;  // (node id, output index)
  std::vector<uint64_t> dependents;                // back-edges
};

struct Graph {
  std::unordered_map<uint64_t, Node> nodes;
  uint64_t next_id = 1;
  uint64_t next_op_nr = 0;
  std::mutex mu;

  Node* get(uint64_t id) {
    auto it = nodes.find(id);
    return it == nodes.end() ? nullptr : &it->second;
  }
};

bool storages_intersect(const Node& a, const Node& b) {
  for (uint64_t s : a.storages)
    for (uint64_t t : b.storages)
      if (s == t) return true;
  return false;
}

// Latest node mutating `node`'s storages: walks BOTH dependent and
// dependency edges through storage-aliasing nodes. (The reference walks
// dependents only, deferred_init.cc:537-575, which misses in-place ops
// recorded against a view's base fake; see _graph.py last_in_place_node.)
Node* last_in_place(Graph& g, Node& node) {
  Node* last = &node;
  std::unordered_set<uint64_t> seen{node.id};
  std::vector<uint64_t> stack{node.id};
  while (!stack.empty()) {
    Node* n = g.get(stack.back());
    stack.pop_back();
    if (!n) continue;
    auto consider = [&](uint64_t mid) {
      if (seen.count(mid)) return;
      seen.insert(mid);
      Node* m = g.get(mid);
      if (!m || !storages_intersect(*m, node)) return;
      if (m->op_nr > last->op_nr) last = m;
      stack.push_back(mid);
    };
    for (uint64_t d : n->dependents) consider(d);
    for (auto& [dep_id, idx] : n->deps) consider(dep_id);
  }
  return last;
}

// Port of OpNode.build_call_stack (torchdistx_tpu/_graph.py), which in
// turn mirrors buildCallStack/collectCallStack
// (deferred_init.cc:526-618): dependency closure of the last in-place
// node, plus aliasing dependents up to it, plus clobbered readers, to a
// fixpoint; sorted chronologically.
std::vector<uint64_t> build_call_stack(Graph& g, Node& node) {
  Node* last = last_in_place(g, node);
  std::unordered_map<uint64_t, Node*> included;

  std::vector<Node*> visit_stack;
  auto visit = [&](Node* n) {
    visit_stack.push_back(n);
    while (!visit_stack.empty()) {
      Node* cur = visit_stack.back();
      visit_stack.pop_back();
      if (included.count(cur->id)) continue;
      included.emplace(cur->id, cur);
      for (auto& [dep_id, idx] : cur->deps) {
        Node* dep = g.get(dep_id);
        if (dep && !dep->materialized && !included.count(dep->id))
          visit_stack.push_back(dep);
      }
    }
  };

  visit(&node);
  if (last != &node) visit(last);

  bool changed = true;
  while (changed) {
    changed = false;
    std::vector<Node*> snapshot;
    snapshot.reserve(included.size());
    for (auto& [id, n] : included) snapshot.push_back(n);
    // The alias FRONTIER: included nodes plus the transitive alias
    // closure over them, in BOTH directions.  Materialized nodes are
    // never replayed, but their cached outputs carry the aliasing
    // relation — dependencies reach the storage's base, and materialized
    // aliasing DEPENDENTS reach the rest of the alias web hanging off it
    // (a data-read/in-place chain on the base), whose own non-aliasing
    // readers (clone/deepcopy) are clobbered by an included mutator of
    // the shared storage just the same (mirrors the Python walk; soak
    // fuzzer seeds 1465/1537).
    std::vector<Node*> frontier(snapshot);
    std::unordered_set<uint64_t> fseen;
    for (Node* f : frontier) fseen.insert(f->id);
    for (size_t fi = 0; fi < frontier.size(); ++fi) {
      Node* f = frontier[fi];
      for (auto& [dep_id, idx] : f->deps) {
        Node* dep = g.get(dep_id);
        if (dep && !fseen.count(dep->id)) {
          fseen.insert(dep->id);
          frontier.push_back(dep);
        }
      }
      for (uint64_t d_id : f->dependents) {
        Node* d = g.get(d_id);
        if (d && !fseen.count(d_id) && d->materialized &&
            storages_intersect(*d, *f)) {
          fseen.insert(d_id);
          frontier.push_back(d);
        }
      }
    }
    // (a) aliasing dependents of any frontier node, up to the last
    // in-place node.
    for (Node* f : frontier) {
      for (uint64_t d_id : f->dependents) {
        Node* d = g.get(d_id);
        if (!d || included.count(d->id) || d->materialized) continue;
        if (d->op_nr <= last->op_nr && storages_intersect(*d, *f)) {
          visit(d);
          changed = true;
        }
      }
    }
    // (b) readers clobbered by a later included mutation of a storage an
    // earlier frontier node aliases.  Indexed by storage key so the scan
    // touches only genuinely aliasing (n, v) pairs.
    std::unordered_map<uint64_t, std::vector<Node*>> carriers_by_storage;
    for (Node* v : frontier)
      for (uint64_t sk : v->storages) carriers_by_storage[sk].push_back(v);
    for (Node* n : snapshot) {
      std::unordered_set<uint64_t> seen_v;
      for (uint64_t sk : n->storages) {
        auto it = carriers_by_storage.find(sk);
        if (it == carriers_by_storage.end()) continue;
        for (Node* v : it->second) {
          if (v == n || seen_v.count(v->id) || v->op_nr >= n->op_nr) continue;
          seen_v.insert(v->id);
          for (uint64_t r_id : v->dependents) {
            Node* r = g.get(r_id);
            if (!r || included.count(r_id) || r->materialized) continue;
            if (r->op_nr < n->op_nr && !storages_intersect(*r, *v)) {
              visit(r);
              changed = true;
            }
          }
        }
      }
    }
  }

  std::vector<Node*> sorted;
  sorted.reserve(included.size());
  for (auto& [id, n] : included) sorted.push_back(n);
  std::sort(sorted.begin(), sorted.end(),
            [](Node* a, Node* b) { return a->op_nr < b->op_nr; });
  std::vector<uint64_t> ids;
  ids.reserve(sorted.size());
  for (Node* n : sorted) ids.push_back(n->id);
  return ids;
}

}  // namespace

TDX_API void* tdx_graph_create() { return new Graph(); }

TDX_API void tdx_graph_destroy(void* gp) { delete static_cast<Graph*>(gp); }

TDX_API uint64_t tdx_node_create(void* gp) {
  Graph& g = *static_cast<Graph*>(gp);
  std::lock_guard<std::mutex> lock(g.mu);
  uint64_t id = g.next_id++;
  Node& n = g.nodes[id];
  n.id = id;
  n.op_nr = g.next_op_nr++;
  return id;
}

// Destroy a node, erasing its back-edges from its dependencies (the
// reference's OpNode destructor semantics, deferred_init.cc:409-411).
TDX_API void tdx_node_destroy(void* gp, uint64_t id) {
  Graph& g = *static_cast<Graph*>(gp);
  std::lock_guard<std::mutex> lock(g.mu);
  Node* n = g.get(id);
  if (!n) return;
  for (auto& [dep_id, idx] : n->deps) {
    Node* dep = g.get(dep_id);
    if (!dep) continue;
    auto& v = dep->dependents;
    v.erase(std::remove(v.begin(), v.end(), id), v.end());
  }
  g.nodes.erase(id);
}

TDX_API void tdx_node_add_storage(void* gp, uint64_t id, uint64_t key) {
  Graph& g = *static_cast<Graph*>(gp);
  std::lock_guard<std::mutex> lock(g.mu);
  Node* n = g.get(id);
  if (n) n->storages.push_back(key);
}

TDX_API void tdx_node_add_dep(void* gp, uint64_t id, uint64_t dep_id,
                              int32_t out_idx) {
  Graph& g = *static_cast<Graph*>(gp);
  std::lock_guard<std::mutex> lock(g.mu);
  Node* n = g.get(id);
  Node* dep = g.get(dep_id);
  if (!n || !dep) return;
  n->deps.emplace_back(dep_id, out_idx);
  dep->dependents.push_back(id);
}

TDX_API void tdx_node_set_materialized(void* gp, uint64_t id, int32_t value) {
  Graph& g = *static_cast<Graph*>(gp);
  std::lock_guard<std::mutex> lock(g.mu);
  Node* n = g.get(id);
  if (n) n->materialized = value != 0;
}

TDX_API uint64_t tdx_last_in_place(void* gp, uint64_t id) {
  Graph& g = *static_cast<Graph*>(gp);
  std::lock_guard<std::mutex> lock(g.mu);
  Node* n = g.get(id);
  if (!n) return 0;
  return last_in_place(g, *n)->id;
}

// Writes up to `cap` node ids (chronological order) into `out`; returns
// the total count (call again with a bigger buffer if count > cap).
TDX_API uint64_t tdx_build_call_stack(void* gp, uint64_t id, uint64_t* out,
                                      uint64_t cap) {
  Graph& g = *static_cast<Graph*>(gp);
  std::lock_guard<std::mutex> lock(g.mu);
  Node* n = g.get(id);
  if (!n) return 0;
  std::vector<uint64_t> ids = build_call_stack(g, *n);
  uint64_t count = ids.size();
  for (uint64_t i = 0; i < count && i < cap; ++i) out[i] = ids[i];
  return count;
}
