/* Public C API of the tdxgraph native engine.
 *
 * Counterpart of the reference's installed public headers
 * (reference src/cc/torchdistx/{fake,deferred_init}.h, installed by its
 * src/cc/torchdistx/CMakeLists.txt) — but as a flat C ABI so it is
 * consumable from ctypes (torchdistx_tpu/_native.py), C, or C++ without
 * any torch/ABI coupling.
 *
 * Thread safety: every call locks the graph's internal mutex; handles may
 * be shared across threads.  Node ids are stable for the graph's
 * lifetime; 0 is never a valid id.
 */
#ifndef TDX_GRAPH_H
#define TDX_GRAPH_H

#include <stdint.h>

#if defined(_WIN32)
#ifdef TDX_BUILDING_DLL /* defined when compiling the library itself */
#define TDX_PUBLIC __declspec(dllexport)
#else
#define TDX_PUBLIC __declspec(dllimport)
#endif
#else
#define TDX_PUBLIC
#endif

#ifdef __cplusplus
extern "C" {
#endif

/* Create / destroy an operation graph. */
TDX_PUBLIC void* tdx_graph_create(void);
TDX_PUBLIC void tdx_graph_destroy(void* graph);

/* Create a node (returns its id; op_nr ordering is creation order). */
TDX_PUBLIC uint64_t tdx_node_create(void* graph);

/* Destroy a node, erasing its back-edges from its dependencies. */
TDX_PUBLIC void tdx_node_destroy(void* graph, uint64_t id);

/* Record that output storage `key` belongs to node `id` (alias key). */
TDX_PUBLIC void tdx_node_add_storage(void* graph, uint64_t id, uint64_t key);

/* Record a dependency: node `id` consumes output `out_idx` of `dep_id`. */
TDX_PUBLIC void tdx_node_add_dep(void* graph, uint64_t id, uint64_t dep_id,
                                 int32_t out_idx);

/* Mark a node (not) materialized; materialized nodes are pruned from
 * call-stack builds. */
TDX_PUBLIC void tdx_node_set_materialized(void* graph, uint64_t id,
                                          int32_t value);

/* Last (by op_nr) node whose outputs alias `id`'s storages — the replay
 * horizon for in-place chains. */
TDX_PUBLIC uint64_t tdx_last_in_place(void* graph, uint64_t id);

/* Write up to `cap` node ids (chronological replay order for
 * materializing `id`) into `out`; returns the total count — call again
 * with a larger buffer if the count exceeds `cap`. */
TDX_PUBLIC uint64_t tdx_build_call_stack(void* graph, uint64_t id,
                                         uint64_t* out, uint64_t cap);

#ifdef __cplusplus
} /* extern "C" */
#endif

#endif /* TDX_GRAPH_H */
